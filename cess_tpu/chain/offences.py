"""Offences pallet: portable misbehavior evidence → deferred slashing.

Role match: the reference wires `pallet_im_online` + `pallet_offences`
+ `pallet_session::historical` into its runtime (reference:
runtime/src/lib.rs:1509-1527) so that

 * a validator proven to have EQUIVOCATED (two signatures over
   conflicting consensus payloads at one height/slot) loses bonded
   stake and is chilled — GRANDPA's accountable-safety contract
   (Stewart & Kokoris-Kogias 2020: equivocation evidence must feed an
   on-chain slashing pipeline, PAPERS.md);
 * a validator that stays SILENT for a whole session (no signed
   im-online heartbeat) is chilled out of the next election and its
   scheduler credit punished — the offline-stake tolerance Ouroboros
   Praos requires of stake-weighted leader election (David et al.
   2018, PAPERS.md).

This pallet owns both capabilities for the framework's deterministic
runtime:

  evidence     `OffenceReport` is a PORTABLE, independently
               re-verifiable proof: two (payload, signature) pairs
               over conflicting consensus payloads, re-checked by
               `verify_report` on EVERY replica before anything is
               queued — one honest observer convicts everywhere, and
               a forged or replayed report is a deterministic no-op.
  registry     reports are deduplicated by (kind, offender, session):
               at most one conviction per offender per kind per
               session, no matter how many honest reporters race.
  heartbeats   `heartbeat` is a signed per-session extrinsic submitted
               by each authority's offchain worker (node/service.py);
               the end-of-session sweep (`session_sweep`, registered
               as a session observer) reports every authority that
               never checked in.  A session with ZERO heartbeats is
               skipped — header-less sims and single-node dev chains
               never run the OCW and must not chill their whole set.
  deferral     convictions queue in `pending` and apply at the ERA
               boundary (`apply_pending`, called by session.py just
               before the election) in sorted order, so every replica
               applies the same slashes in the same block — and the
               election that follows already sees the chills.

Severity schedule (docs/offences.md):

  equivocation    slash `5% · 2^strikes` of the offender's bonded
                  stake (capped at 100%; `strikes` counts the
                  offender's prior equivocation convictions) into the
                  treasury pot, plus a 2-era chill.
  unresponsive    no slash; 1-era chill + one scheduler-credit
                  punishment (the im-online "chill only" mode the
                  reference runs with, lib.rs:1509).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

from .session import HISTORY_DEPTH_SESSIONS
from .state import ChainState
from .types import AccountId, ensure

MOD = "offences"

KIND_VOTE_EQUIV = "equivocation.vote"
KIND_BLOCK_EQUIV = "equivocation.block"
KIND_UNRESPONSIVE = "unresponsive"
EVIDENCE_KINDS = (KIND_VOTE_EQUIV, KIND_BLOCK_EQUIV)

# Base equivocation slash, doubled per prior conviction of the same
# offender (5 → 10 → 20 → … → 100%).
EQUIVOCATION_SLASH_PERCENT = 5
# Eras the offender sits out of the election after conviction (the
# first era it may `validate` again is active_era + 1 + chill_eras).
CHILL_ERAS_EQUIVOCATION = 2
CHILL_ERAS_UNRESPONSIVE = 1
# Evidence older than this many sessions is refused, and applied
# records older than it are pruned.  Derived from the session pallet's
# historical depth (single source of truth) minus one: at session
# index i the pallet has already pruned set i-DEPTH, so the oldest
# session whose membership is still provable is i-(DEPTH-1).
REPORT_HISTORY_SESSIONS = HISTORY_DEPTH_SESSIONS - 1
# Evidence may also name a slightly FUTURE height (a double-vote for an
# upcoming finality boundary is proven the moment both signatures
# exist); membership for future sessions is checked against the live
# set.  Bounded so nonsense heights stay refusable.
FUTURE_SESSION_SLACK = 2


# ------------------------------------------------------------ evidence


@dataclass
class OffenceReport:
    """A portable offence proof: two (payload_hex, sig_hex) pairs over
    conflicting consensus payloads, both signed by `offender`.  The
    payloads are the exact canonical-JSON bytes the node layer signs
    (node/sync.py finality_payload / Block.signing_payload), so any
    replica can re-verify the report with nothing but the offender's
    registered BLS key — the report is the proof."""

    kind: str
    offender: AccountId
    session: int
    evidence: list = field(default_factory=list)  # [[payload_hex, sig_hex], …]

    def key(self) -> tuple:
        return (self.kind, self.offender, self.session)

    def digest(self) -> str:
        h = hashlib.blake2b(digest_size=16)
        for pair in sorted(tuple(p) for p in self.evidence):
            for part in pair:
                h.update(str(part).encode() + b"\x00")
        return h.hexdigest()

    def to_json(self) -> dict:
        return {
            "kind": self.kind, "offender": self.offender,
            "session": self.session,
            "evidence": [list(p) for p in self.evidence],
        }

    @classmethod
    def from_json(cls, d: dict) -> "OffenceReport":
        return cls(
            kind=str(d["kind"]), offender=str(d["offender"]),
            session=int(d["session"]),
            evidence=[[str(p), str(s)] for p, s in d["evidence"]],
        )


def _decode_evidence(report: OffenceReport):
    """evidence → [(payload bytes, sig bytes, parsed payload list), …]
    or None when anything is malformed."""
    if len(report.evidence) != 2:
        return None
    out = []
    for pair in report.evidence:
        if len(pair) != 2:
            return None
        try:
            payload = bytes.fromhex(pair[0])
            sig = bytes.fromhex(pair[1])
            parsed = json.loads(payload)
        except (ValueError, TypeError):
            return None
        if not isinstance(parsed, list):
            return None
        out.append((payload, sig, parsed))
    return out


def evidence_height(report: OffenceReport) -> int | None:
    """The chain height both payloads name (index 2 of the finality AND
    block signing payloads) — the anchor that pins the report to a
    session deterministically on every replica."""
    decoded = _decode_evidence(report)
    if decoded is None:
        return None
    n = decoded[0][2][2] if len(decoded[0][2]) > 2 else None
    return n if isinstance(n, int) else None


def verify_report(report: OffenceReport, genesis: str, key_lookup) -> bool:
    """Full independent re-verification — the gate every replica runs
    before an offence enters the registry:

      * exactly two DISTINCT payloads, both on OUR chain (genesis
        prefix) and of the kind claimed;
      * vote equivocation: two finality payloads for the SAME height
        and DIFFERENT block hashes;
      * block equivocation: two header payloads for the SAME slot,
        both naming the offender as author;
      * both signatures verify under the offender's registered key.

    Anything else — forged signatures, stolen payload pairs, evidence
    for another chain, same-payload "conflicts" — returns False, so an
    unverifiable report is a no-op on every replica (the acceptance
    regression in tests/test_offences.py)."""
    from ..ops import bls12_381 as bls

    if report.kind not in EVIDENCE_KINDS:
        return False
    pk = key_lookup(report.offender)
    if pk is None:
        return False
    decoded = _decode_evidence(report)
    if decoded is None:
        return False
    (p1, s1, j1), (p2, s2, j2) = decoded
    if p1 == p2:
        return False
    if report.kind == KIND_VOTE_EQUIV:
        # node/sync.py finality_payload: [genesis, "finality", n, hash]
        for j in (j1, j2):
            if len(j) != 4 or j[0] != genesis or j[1] != "finality":
                return False
            if not isinstance(j[2], int):
                return False
        if j1[2] != j2[2] or j1[3] == j2[3]:
            return False
    else:
        # node/sync.py Block.signing_payload: [genesis, "block", n,
        # slot, parent, author, ext_root, state, vrf_out, vrf_proof]
        for j in (j1, j2):
            if len(j) != 10 or j[0] != genesis or j[1] != "block":
                return False
            if not isinstance(j[2], int) or not isinstance(j[3], int):
                return False
            if j[5] != report.offender:
                return False
        if j1[3] != j2[3]:
            return False  # different slots: not an equivocation
    return bls.verify(pk, p1, s1) and bls.verify(pk, p2, s2)


# ------------------------------------------------------------ registry


@dataclass
class OffenceRecord:
    """One registry entry: the conviction bookkeeping that travels in
    the state (checkpoint blob v4)."""

    kind: str
    offender: AccountId
    session: int
    digest: str
    reporter: AccountId
    applied: bool = False


class OffencesPallet:
    def __init__(self, state: ChainState, staking, scheduler_credit) -> None:
        self.state = state
        self.staking = staking
        self.scheduler_credit = scheduler_credit
        # Wired by the Runtime after SessionPallet exists (mutual refs).
        self.session = None
        # Injected by the node layer: report → bool, closing over the
        # node's genesis hash and key registry.  Wiring, never state —
        # a runtime without one REFUSES every evidence report.
        self.evidence_verifier = None
        # (kind, offender, session) → OffenceRecord — the dedup + audit
        # trail; `pending` queues keys for the era-boundary application.
        self.reports: dict[tuple, OffenceRecord] = {}
        self.pending: list = []
        # session index → authorities that heartbeat that session
        self.heartbeats: dict[int, set] = {}
        # offender → prior equivocation convictions (escalation input)
        self.strikes: dict[AccountId, int] = {}

    def known(self, key: tuple) -> bool:
        return tuple(key) in self.reports

    # ------------------------------------------------------ heartbeats

    def heartbeat(self, sender: AccountId, session_index) -> None:
        """Signed im-online heartbeat (reference: im-online
        lib.rs:342-359): one per authority per session, only for the
        CURRENT session — the nonce gate already blocks replays, this
        gate blocks hoarding heartbeats for future sessions."""
        ensure(self.session is not None, MOD, "NoSession")
        ensure(isinstance(session_index, int), MOD, "BadSessionIndex")
        ensure(
            sender in self.staking.validators, MOD, "NotAnAuthority"
        )
        ensure(
            session_index == self.session.session_index, MOD,
            "StaleHeartbeat",
        )
        beats = self.heartbeats.setdefault(session_index, set())
        ensure(sender not in beats, MOD, "DuplicateHeartbeat")
        beats.add(sender)
        self.state.deposit_event(
            MOD, "Heartbeat", who=sender, session=session_index
        )

    def session_sweep(self, ending_index: int, ending_validators) -> None:
        """End-of-session liveness sweep (session observer): every
        active authority with no heartbeat for the ended session is
        reported unresponsive — but ONLY when at least HALF the ending
        set did heartbeat.  A mostly-silent session means the NETWORK
        (or this fork) was degraded, not the validators: chilling on
        such evidence collapses the authority set to whoever's
        heartbeats happened to land and turns a transient partition
        into a permanent one.  The zero-heartbeat case also covers
        runtimes that never run the heartbeat OCW (header-less sims,
        single-node dev): they must not chill their own set."""
        beats = self.heartbeats.get(ending_index, set())
        present = sum(1 for v in ending_validators if v in beats)
        if present and 2 * present >= len(ending_validators):
            for v in ending_validators:
                if v not in beats:
                    self.report_unresponsive(v, ending_index)
        for s in [s for s in self.heartbeats if s <= ending_index]:
            del self.heartbeats[s]

    # ------------------------------------------------------ reporting

    def report_unresponsive(self, offender: AccountId, session: int) -> None:
        """Internal intake for the sweep: derived purely from on-chain
        heartbeat state, so every replica reports identically.  Not
        reachable through an extrinsic — silence cannot be forged."""
        key = (KIND_UNRESPONSIVE, offender, session)
        if key in self.reports:
            return
        digest = hashlib.blake2b(
            b"offences/silent" + offender.encode()
            + session.to_bytes(8, "little"),
            digest_size=16,
        ).hexdigest()
        self._enqueue(OffenceRecord(
            kind=KIND_UNRESPONSIVE, offender=offender, session=session,
            digest=digest, reporter="",
        ))

    def report_offence(self, sender: AccountId, report_json: dict) -> None:
        """Extrinsic intake for evidence-backed offences (the
        offences::report role).  Every check is deterministic on-chain
        state plus the independent evidence re-verification, so a
        forged, mis-sessioned, unslashable, or duplicate report fails
        with the SAME receipt on every replica."""
        try:
            report = OffenceReport.from_json(report_json)
        except (KeyError, TypeError, ValueError):
            ensure(False, MOD, "MalformedReport")
        ensure(report.kind in EVIDENCE_KINDS, MOD, "UnknownOffenceKind")
        ensure(self.session is not None, MOD, "NoSession")
        ensure(
            self.evidence_verifier is not None
            and self.evidence_verifier(report),
            MOD, "UnverifiableEvidence",
        )
        height = evidence_height(report)
        ensure(height is not None, MOD, "MalformedReport")
        ensure(
            report.session == self.session.session_of_block(height),
            MOD, "WrongSession",
        )
        current = self.session.session_index
        ensure(
            report.session - current <= FUTURE_SESSION_SLACK
            and current - report.session <= REPORT_HISTORY_SESSIONS,
            MOD, "SessionOutOfRange",
        )
        # membership: historical set for past sessions, the LIVE set
        # for the current/near-future ones (a double-vote for an
        # upcoming boundary is proven before its session starts)
        members = self.session.validators_at(min(report.session, current))
        ensure(
            members is not None and report.offender in members,
            MOD, "NotAValidatorThen",
        )
        ensure(report.offender in self.staking.ledger, MOD, "NothingToSlash")
        ensure(report.key() not in self.reports, MOD, "DuplicateOffence")
        self._enqueue(OffenceRecord(
            kind=report.kind, offender=report.offender,
            session=report.session, digest=report.digest(),
            reporter=sender,
        ))

    def _enqueue(self, rec: OffenceRecord) -> None:
        key = (rec.kind, rec.offender, rec.session)
        self.reports[key] = rec
        self.pending.append(key)
        self.state.deposit_event(
            MOD, "OffenceReported", kind=rec.kind, offender=rec.offender,
            session=rec.session, digest=rec.digest,
        )

    # ------------------------------------------------------ application

    def apply_pending(self) -> int:
        """Era-boundary conviction pass (called by session.py BEFORE
        staking.end_era and the election, so the election that follows
        already excludes the chilled).  Sorted key order makes the
        application sequence — and therefore every balance — identical
        on every replica regardless of report arrival order.  Returns
        the number of offences applied."""
        applied = 0
        for key in sorted(set(tuple(k) for k in self.pending)):
            rec = self.reports.get(key)
            if rec is None or rec.applied:
                continue
            if rec.kind in EVIDENCE_KINDS:
                strikes = self.strikes.get(rec.offender, 0)
                percent = min(100, EQUIVOCATION_SLASH_PERCENT << strikes)
                self.strikes[rec.offender] = strikes + 1
                slashed = self.staking.slash_offender(rec.offender, percent)
                self.staking.force_chill(
                    rec.offender,
                    self.staking.active_era + 1 + CHILL_ERAS_EQUIVOCATION,
                )
                self.state.deposit_event(
                    MOD, "OffenderSlashed", offender=rec.offender,
                    kind=rec.kind, amount=slashed, percent=percent,
                )
            else:
                self.staking.force_chill(
                    rec.offender,
                    self.staking.active_era + 1 + CHILL_ERAS_UNRESPONSIVE,
                )
                controller = self.staking.bonded.get(
                    rec.offender, rec.offender
                )
                self.scheduler_credit.record_punishment(controller)
                self.state.deposit_event(
                    MOD, "OffenderChilled", offender=rec.offender,
                    session=rec.session,
                )
            rec.applied = True
            applied += 1
        self.pending = []
        # Applied records past the evidence-acceptance horizon can
        # never be re-reported (SessionOutOfRange) — prune them so the
        # registry stays bounded on long chains.  Records AT the
        # horizon must survive: report_offence still accepts that
        # session, so pruning it would let a stored old report convict
        # the same offender twice.
        if self.session is not None:
            horizon = self.session.session_index - REPORT_HISTORY_SESSIONS
            if horizon > 0:
                self.reports = {
                    k: r for k, r in self.reports.items()
                    if not r.applied or r.session >= horizon
                }
        return applied
