"""Multi-role node simulator: the integration harness (SURVEY.md §7 L4).

Replaces the reference's mock-runtime test style (reference:
c-pallets/audit/src/mock.rs:36-58 wires ~15 real pallets and fakes
randomness so multi-role behavior runs in one process) with a deterministic
block-loop simulation in which every role is an actor against one Runtime:

  user       — RS-encodes content into segments (ops/rs.py, TPU kernel),
               declares uploads, owns buckets;
  miner      — stores fragments + fillers, reports transfers, answers audit
               challenges with real PoDR2 proofs (ProofBackend.prove_batch);
  TEE worker — holds the PoDR2 secret, tags fragments during the deal's
               Calculate stage (reference rate assumption:
               c-pallets/file-bank/src/constants.rs:4) and tags fillers,
               verifies proof batches (ProofBackend.verify_batch), signs
               verdicts with its BLS node key;
  validator  — commits challenges through the 2/3 quorum.

Off-chain channels (miner→TEE proof delivery, TEE→miner tag delivery) are
in-process queues; on-chain the audit pallet carries only σ plus a binding
commitment, matching the reference's ≤ SigmaMax blobs
(c-pallets/audit/src/types.rs:36-40).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field

import numpy as np

from ..ops import bls12_381 as bls
from ..ops import podr2
from ..ops.podr2 import Challenge, Podr2Params, Podr2Proof
from ..ops.rs import RSStream, segment_code
from ..proof import ProofBackend, get_backend, ias
from ..proof.backend import ProveRequest
from ..utils.hashing import Hash64
from .file_bank import FillerInfo, SegmentList, UserBrief
from .runtime import Runtime, RuntimeConfig
from .tee_worker import SgxAttestationReport
from .types import TOKEN


@dataclass
class StoredFragment:
    name: bytes
    data: bytes
    tags: list[bytes] | None = None  # None until the TEE tags it


@dataclass
class MinerStore:
    fragments: dict[Hash64, StoredFragment] = field(default_factory=dict)
    fillers: dict[Hash64, StoredFragment] = field(default_factory=dict)


from functools import lru_cache


@lru_cache(maxsize=1)
def _sim_authority():
    """Deterministic fixture root, generated once per process (the RSA
    prime search is ~0.1 s and the output is seed-fixed)."""
    # cesslint: allow[det-random] fixed-seed fixture RNG — every replica
    # derives the identical IAS root from b"sim-ias-root"
    return ias.fixture_authority(random.Random(b"sim-ias-root"), bits=1024)


@lru_cache(maxsize=8)
def _sim_report(podr2_pbk: bytes):
    """Deterministic attestation triple for a worker key, cached — every
    NodeSim with the same key reproduces the identical report."""
    _, root_priv = _sim_authority()
    report_json = (
        b'{"isvEnclaveQuoteStatus":"OK","podr2_pbk":"'
        + podr2_pbk.hex().encode()
        + b'"}'
    )
    return ias.fixture_report(
        root_priv,
        report_json,
        # cesslint: allow[det-random] fixed-seed fixture RNG keyed on the
        # worker pubkey — deterministic across replicas by construction
        random.Random(b"sim-tee-report" + podr2_pbk),
        bits=1024,
    )


class NodeSim:
    def __init__(
        self,
        n_miners: int = 5,
        n_validators: int = 3,
        backend: str | ProofBackend = "cpu",
        params: Podr2Params = Podr2Params(n=8, s=4),
        config: RuntimeConfig | None = None,
    ) -> None:
        self.params = params
        self.backend = (
            backend if isinstance(backend, ProofBackend) else get_backend(backend)
        )
        self.miners = [f"miner-{i}" for i in range(n_miners)]
        self.validators = [f"validator-{i}" for i in range(n_validators)]
        self.users: list[str] = []

        cfg = config or RuntimeConfig(
            podr2_chunk_count=params.n,
            endowed={
                "tee-stash": 1_000_000 * TOKEN,
                "tee-ctrl": 1_000 * TOKEN,
                **{m: 1_000_000 * TOKEN for m in self.miners},
            },
        )
        cfg.podr2_chunk_count = params.n
        # Attestation genesis: a fixture authority plays the Intel IAS
        # root's role (reference pins the real root DER at
        # primitives/enclave-verify/src/lib.rs:46-93); registration goes
        # through the full X.509 + RSA verification path.  The fixture
        # root is appended to any caller-pinned store so the sim's own
        # TEE can still register under it.
        self.ias_root_der, self.ias_root_priv = _sim_authority()
        fixture_store = ias.RootStore.from_der([self.ias_root_der])
        if cfg.ias_roots is None:
            cfg.ias_roots = fixture_store
        else:
            cfg.ias_roots = ias.RootStore(
                tuple(cfg.ias_roots.roots) + fixture_store.roots
            )
        self.rt = Runtime(cfg)
        self.rt.run_blocks(1)

        # TEE worker: PoDR2 keypair is the network key; node key is a BLS
        # key whose signatures the audit pallet verifies (the seam the
        # reference leaves open at audit/src/lib.rs:484).
        self.tee_acc = "tee-ctrl"
        self.tee_sk, self.tee_pk = podr2.keygen(b"sim-tee")
        self.tee_node_sk = bls.keygen(b"sim-tee-node")
        node_key = bls.sk_to_pk(self.tee_node_sk)
        self.rt.staking.bond("tee-stash", self.tee_acc, 100_000 * TOKEN)
        self.rt.tee_worker.register(
            self.tee_acc, "tee-stash", node_key, b"tee-peer", self.tee_pk,
            self.make_attestation(self.tee_pk),
        )
        self.rt.audit.result_verifier = lambda nk, msg, sig: bls.verify(
            nk, msg, sig
        )

        self.rt.audit.initialize_keys(self.validators)

        self.store: dict[str, MinerStore] = {}
        for m in self.miners:
            self.rt.sminer.regnstk(m, f"{m}-ben", m.encode(), 8_000 * TOKEN)
            self.store[m] = MinerStore()

        # Off-chain mail: TEE inbox of (miner, idle items, service items).
        self.tee_inbox: list[tuple] = []
        self._rs = segment_code()

    # ------------------------------------------------------------ helpers

    def make_attestation(self, podr2_pbk: bytes) -> SgxAttestationReport:
        """Fabricate an attestation report signed under the sim's pinned
        authority (the reference's own tests round-trip fixtures the same
        way, enclave-verify/src/lib.rs:242-255).  The report body binds
        the worker's PoDR2 public key (checked at registration —
        proof/ias.report_binds_key)."""
        sign, cert_b64, report = _sim_report(podr2_pbk)
        return SgxAttestationReport(
            report_json_raw=report, sign=sign, cert_der=cert_b64
        )

    @property
    def segment_bytes(self) -> int:
        """A sim 'segment' is 2 data fragments (the RS(2,1) geometry of the
        reference: 16 MiB segment = 2×8 MiB data + 1×8 MiB parity)."""
        return 2 * self.params.fragment_bytes

    def add_user(self, name: str, gib: int = 1, tokens: int = 10**6) -> None:
        self.rt.state.balances.mint(name, tokens * TOKEN)
        self.rt.storage_handler.buy_space(name, gib)
        self.users.append(name)

    # ------------------------------------------------------------ fillers

    def miner_add_fillers(self, miner: str, count: int) -> None:
        """Miner requests `count` TEE-tagged fillers and reports them
        on-chain (reference: file-bank/src/lib.rs:804-842, ≤10 per call)."""
        fillers = []
        for _ in range(count):
            seq = len(self.store[miner].fillers)
            fh = Hash64.of(f"filler/{miner}/{seq}".encode())
            data = podr2.filler_data(fh.raw(), self.params)
            tags = podr2.tag_fragment(
                self.tee_sk, fh.ascii_bytes(), data, self.params
            )
            self.store[miner].fillers[fh] = StoredFragment(
                name=fh.ascii_bytes(), data=data, tags=tags
            )
            fillers.append(
                FillerInfo(
                    block_num=self.rt.state.block_number,
                    miner_address=miner,
                    filler_hash=fh,
                )
            )
        for start in range(0, len(fillers), 10):
            self.rt.file_bank.upload_filler(
                miner, self.tee_acc, fillers[start : start + 10]
            )

    # ------------------------------------------------------------ upload

    def user_upload(self, user: str, file_name: str, content: bytes):
        """Full upload pipeline: RS-encode → declare → deliver fragments →
        transfer reports → TEE tag calculation → file Active."""
        seg_bytes = self.segment_bytes
        frag_bytes = self.params.fragment_bytes
        content_padded = content.ljust(
            ((len(content) + seg_bytes - 1) // seg_bytes) * seg_bytes or seg_bytes,
            b"\x00",
        )
        deal_info: list[SegmentList] = []
        fragment_payload: dict[Hash64, bytes] = {}
        # All segments RS-encode as ONE streamed batch (fixed-slab
        # dispatches; multi-segment files stop paying a device round
        # trip per segment).
        segments = np.frombuffer(content_padded, dtype=np.uint8).reshape(
            -1, 2, frag_bytes
        )
        parities = RSStream(self._rs).run_batch(segments)
        for shards, parity in zip(segments, parities):
            all_shards = [shards[0], shards[1], parity[0]]
            frag_hashes = []
            for shard in all_shards:
                payload = shard.tobytes()
                fh = Hash64.of(payload)
                fragment_payload[fh] = payload
                frag_hashes.append(fh)
            deal_info.append(
                SegmentList(
                    hash=Hash64.of(shards.tobytes()), fragment_list=frag_hashes
                )
            )
        file_hash = Hash64.of(b"file:" + content_padded)
        brief = UserBrief(user=user, file_name=file_name, bucket_name=f"{user}-bkt")
        self.rt.file_bank.upload_declaration(
            user, file_hash, deal_info, brief, len(content)
        )

        # Miners fetch their assigned fragments and report.
        deal = self.rt.file_bank.deal_map[file_hash]
        for mt in deal.assigned_miner:
            for fh in mt.fragment_list:
                self.store[mt.miner].fragments[fh] = StoredFragment(
                    name=fh.ascii_bytes(), data=fragment_payload[fh]
                )
        for mt in list(deal.assigned_miner):
            self.rt.file_bank.transfer_report(mt.miner, [file_hash])

        # Calculate stage: the TEE tags every stored fragment.
        for m in self.miners:
            for frag in self.store[m].fragments.values():
                if frag.tags is None:
                    frag.tags = podr2.tag_fragment(
                        self.tee_sk, frag.name, frag.data, self.params
                    )
        # Let the scheduled calculate_end fire.
        guard = 0
        while file_hash in self.rt.file_bank.deal_map:
            self.rt.next_block()
            guard += 1
            assert guard < 10_000, "calculate_end never fired"
        return file_hash

    def rt_encode(self, shards: np.ndarray):
        return self._rs.encode(shards)

    def recover_file(
        self, file_hash: Hash64, lost: dict[int, int] | None = None
    ) -> bytes:
        """Rebuild a file's plaintext from any k-of-(k+m) stored fragments
        per segment (reference seam: the restoral-order market,
        c-pallets/file-bank/src/lib.rs:936-1125).  `lost` optionally maps
        segment index → fragment index to treat as unavailable on top of
        the on-chain `avail` flags, so different segments recover from
        DIFFERENT survivor sets — the grouped per-pattern rs.RSStream
        path, one batched matmul per distinct erasure mask."""
        f = self.rt.file_bank.file.get(file_hash)
        if f is None:
            raise KeyError(f"unknown file {file_hash}")
        frag_bytes = self.params.fragment_bytes
        k = self._rs.k
        patterns: list[list[int]] = []
        survivors = np.empty(
            (len(f.segment_list), k, frag_bytes), dtype=np.uint8
        )
        for i, seg in enumerate(f.segment_list):
            present: list[int] = []
            for j, frag in enumerate(seg.fragment_list):
                if not frag.avail or (lost is not None and lost.get(i) == j):
                    continue
                stored = self.store[frag.miner].fragments.get(frag.hash)
                if stored is None:
                    continue
                survivors[i, len(present)] = np.frombuffer(
                    stored.data, dtype=np.uint8
                )
                present.append(j)
                if len(present) == k:
                    break
            if len(present) < k:
                raise ValueError(
                    f"segment {i}: only {len(present)} of {k} "
                    "fragments available"
                )
            patterns.append(present)
        data = RSStream(self._rs, present=patterns).run_batch(survivors)
        return data.tobytes()[: f.file_size]

    # ------------------------------------------------------------ audit

    def run_audit_round(self) -> dict[str, tuple[bool, bool]]:
        """One full audit round; returns {miner: (idle_ok, service_ok)}."""
        rt = self.rt
        info = rt.audit.generation_challenge(rt.state.block_number)
        for v in self.validators:
            rt.audit.save_challenge_info(info, v, signature=None)
        assert rt.audit.challenge_snap_shot is not None
        challenge = Challenge.from_net_snapshot(info.net_snap_shot)

        # Challenged miners build proofs over everything they store.
        for snap in list(info.miner_snapshot_list):
            miner = snap.miner
            store = self.store[miner]
            idle = sorted(store.fillers.values(), key=lambda f: f.name)
            service = sorted(store.fragments.values(), key=lambda f: f.name)
            idle_items = self._prove_set(idle, challenge)
            service_items = self._prove_set(service, challenge)
            idle_blob = self._blob(idle_items)
            service_blob = self._blob(service_items)
            rt.audit.submit_proof(miner, idle_blob, service_blob)
            self.tee_inbox.append(
                (miner, idle_blob, service_blob, idle_items, service_items)
            )

        # TEE drains its missions, batch-verifying via the ProofBackend.
        results: dict[str, tuple[bool, bool]] = {}
        seed = rt.state.randomness
        for miner, idle_blob, service_blob, idle_items, service_items in (
            self.tee_inbox
        ):
            tee = next(
                (t for t, lst in rt.audit.unverify_proof.items()
                 if any(p.snap_shot.miner == miner for p in lst)),
                None,
            )
            if tee is None:
                continue
            mission = next(
                p for p in rt.audit.unverify_proof[tee]
                if p.snap_shot.miner == miner
            )
            # Commitment binding: on-chain blob must match delivered proofs.
            idle_ok = mission.idle_prove == self._blob(idle_items)
            service_ok = mission.service_prove == self._blob(service_items)
            idle_ok = idle_ok and all(
                self.backend.verify_batch(
                    self.tee_pk, idle_items, seed, self.params
                )
            )
            service_ok = service_ok and all(
                self.backend.verify_batch(
                    self.tee_pk, service_items, seed, self.params
                )
            )
            sig = bls.sign(
                self.tee_node_sk,
                rt.audit.result_message(miner, idle_ok, service_ok),
            )
            rt.audit.submit_verify_result(tee, miner, idle_ok, service_ok, sig)
            results[miner] = (idle_ok, service_ok)
        self.tee_inbox.clear()
        return results

    def _prove_set(self, frags: list[StoredFragment], challenge: Challenge):
        if not frags:
            return []
        req = ProveRequest(
            names=[f.name for f in frags],
            tags=[f.tags for f in frags],
            data=[f.data for f in frags],
            challenge=challenge,
            params=self.params,
        )
        proofs = self.backend.prove_batch(req)
        return [
            (f.name, challenge, p) for f, p in zip(frags, proofs)
        ]

    @staticmethod
    def _blob(items) -> bytes:
        """≤ SigmaMax on-chain blob: digest binding every (name, proof)."""
        h = hashlib.sha256()
        for name, _, proof in items:
            h.update(name)
            h.update(proof.commitment())
        return h.digest()
