"""TEE worker (scheduler) registry with attestation at the gate.

Re-design of the reference tee-worker pallet (reference:
c-pallets/tee-worker/src/{lib,types}.rs): registration requires (a) the
sender to be the controller bonded to the claimed stash and (b) a valid
attestation report.  The first registered worker's PoDR2 public key becomes
the network-wide `TeePodr2Pk` every proof is verified against.

The attestation check is a pluggable verifier: the reference verifies Intel
IAS reports (X.509 chain to a pinned Intel root + RSA-PKCS1-SHA256 report
signature, reference: primitives/enclave-verify/src/lib.rs:135-219); this
framework's equivalent lives in cess_tpu.proof.ias (hosted X.509/DER parsing
+ batched RSA verify on the xla backend), injected here so unit tests can
use a stub verifier.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from .state import ChainState
from .types import AccountId, ensure

MOD = "tee_worker"


@dataclass
class SgxAttestationReport:
    """reference: tee-worker/src/types.rs:14-19"""

    report_json_raw: bytes
    sign: bytes
    cert_der: bytes


@dataclass
class TeeWorkerInfo:
    """reference: tee-worker/src/types.rs:6-12"""

    controller_account: AccountId
    peer_id: bytes
    node_key: bytes
    stash_account: AccountId


class TeeWorkerPallet:
    def __init__(
        self,
        state: ChainState,
        staking,
        credit_counter,
        cert_verifier: Callable[[bytes, bytes, bytes, bytes], bool] | None = None,
    ) -> None:
        self.state = state
        self.staking = staking
        self.credit_counter = credit_counter
        # verify(sign, cert_der, report_json, podr2_pbk) -> bool; the last
        # argument lets the verifier check the report BINDS the submitted
        # key (replay of someone else's valid attestation must fail).
        self.cert_verifier = cert_verifier
        self.tee_worker_map: dict[AccountId, TeeWorkerInfo] = {}
        self.tee_podr2_pk: bytes | None = None
        self.mr_enclave_whitelist: list[bytes] = []

    # ---------------------------------------------------------------- calls

    def register(
        self,
        sender: AccountId,
        stash_account: AccountId,
        node_key: bytes,
        peer_id: bytes,
        podr2_pbk: bytes,
        sgx_attestation_report: SgxAttestationReport,
    ) -> None:
        """reference: tee-worker/src/lib.rs:136-175"""
        controller = self.staking.bonded_controller(stash_account)
        ensure(controller is not None, MOD, "NotBond")
        ensure(controller == sender, MOD, "NotController")
        ensure(sender not in self.tee_worker_map, MOD, "AlreadyRegistration")
        if self.cert_verifier is not None:
            ensure(
                self.cert_verifier(
                    sgx_attestation_report.sign,
                    sgx_attestation_report.cert_der,
                    sgx_attestation_report.report_json_raw,
                    podr2_pbk,
                ),
                MOD,
                "VerifyCertFailed",
            )
        if len(self.tee_worker_map) == 0:
            self.tee_podr2_pk = podr2_pbk
        self.tee_worker_map[sender] = TeeWorkerInfo(
            controller_account=sender,
            peer_id=peer_id,
            node_key=node_key,
            stash_account=stash_account,
        )
        self.state.deposit_event(
            MOD, "RegistrationTeeWorker", acc=sender, peer_id=peer_id
        )

    def update_whitelist(self, mr_enclave: bytes) -> None:
        """Root call (reference: lib.rs:205-216)."""
        self.mr_enclave_whitelist.append(mr_enclave)

    def exit(self, sender: AccountId) -> None:
        """reference: lib.rs:219-233"""
        self.tee_worker_map.pop(sender, None)
        if len(self.tee_worker_map) == 0:
            self.tee_podr2_pk = None
        self.state.deposit_event(MOD, "Exit", acc=sender)

    # -- ScheduleFind trait (reference: lib.rs:273-307) -------------------

    def contains_scheduler(self, acc: AccountId) -> bool:
        return acc in self.tee_worker_map

    def punish_scheduler(self, acc: AccountId) -> None:
        worker = self.tee_worker_map.get(acc)
        ensure(worker is not None, MOD, "NonTeeWorker")
        self.staking.slash_scheduler(worker.stash_account)
        self.credit_counter.record_punishment(worker.stash_account)

    def get_first_controller(self) -> AccountId:
        for acc in self.tee_worker_map:
            return acc
        ensure(False, MOD, "NonTeeWorker")

    def get_controller_list(self) -> list[AccountId]:
        return list(self.tee_worker_map)
