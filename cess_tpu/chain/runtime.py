"""Runtime composition + deterministic block loop.

The construct_runtime! equivalent (reference: runtime/src/lib.rs:1477-1538):
wires every pallet against the shared ChainState, binds the cross-pallet
traits, and drives the per-block lifecycle —

  block N:  advance clock → refresh shared randomness (the RRSC
            parent-block-randomness stand-in) → on_initialize hooks
            (audit sweeps, file-bank lease sweep, scheduler-credit period
            roll) → dispatch due scheduler agenda calls → (extrinsics
            applied by callers) → era rotation at era boundaries

Determinism contract: given the same genesis + extrinsic sequence, every
replica computes identical state — the replicated-state-machine property the
reference gets from Substrate (SURVEY.md §2 parallelism item 1).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils.hashing import blake2b_256
from .audit import AuditPallet
from .rrsc import RrscPallet
from .cacher import CacherPallet
from .evm import EvmPallet
from .fees import FeesPallet
from .file_bank import FileBankPallet
from .offences import OffencesPallet
from .oss import OssPallet
from .scheduler_credit import SchedulerCreditPallet
from .session import SessionPallet
from .sminer import SminerPallet
from .staking import StakingPallet
from .state import ChainState, ScheduledCall
from .storage_handler import StorageHandlerPallet
from .tee_worker import TeeWorkerPallet
from .types import BLOCKS_PER_DAY, BLOCKS_PER_HOUR, Balance, DispatchError, TOKEN


def session_plan(era_duration_blocks: int, sessions_per_era: int = 0,
                 ) -> tuple[int, int]:
    """(session_length, sessions_per_era) for an era duration: the two
    must multiply back to era_duration_blocks exactly so the session
    clock and the legacy era clock agree on every boundary.  An
    explicit sessions_per_era that divides the era cleanly wins;
    otherwise pick the most sessions ≤ 6 that keep sessions at least 4
    blocks long (heartbeats need a couple of blocks to land before the
    end-of-session sweep reads them)."""
    era = max(1, era_duration_blocks)
    if sessions_per_era > 0:
        if era % sessions_per_era != 0:
            raise ValueError(
                f"sessions_per_era={sessions_per_era} does not divide "
                f"era_duration_blocks={era} — session and era clocks "
                "would disagree on boundaries"
            )
        return era // sessions_per_era, sessions_per_era
    for k in range(6, 1, -1):
        if era % k == 0 and era // k >= 4:
            return era // k, k
    return era, 1


@dataclass
class RuntimeConfig:
    """Genesis knobs (chain-spec equivalent, reference:
    node/src/chain_spec.rs:84-318 + runtime parameter_types)."""

    one_day_block: int = BLOCKS_PER_DAY
    one_hour_block: int = BLOCKS_PER_HOUR
    frozen_days: int = 7
    space_unit_price: Balance = 30 * TOKEN      # per GiB-month
    era_duration_blocks: int = 6 * BLOCKS_PER_HOUR
    eras_per_year: int = 1460
    # Sessions per era (pallet_session; SessionsPerEra=6 in the
    # reference, runtime/src/lib.rs:245).  0 = derive from the era
    # duration (see session_plan); an explicit value must divide it.
    sessions_per_era: int = 0
    credit_period_blocks: int = BLOCKS_PER_DAY
    audit_lock_time: int = 10                   # LockTime (runtime lib.rs:994)
    podr2_chunk_count: int = 1024               # CHUNK_COUNT (common lib.rs:62)
    genesis_randomness: bytes = bytes(32)
    endowed: dict = field(default_factory=dict)  # account -> free balance
    # Genesis authority set: bonded + seated at block 0 (the chain-spec
    # session-keys/staking genesis role, node/src/chain_spec.rs:84-318),
    # so rrsc.slot_author rotates over them from the first slot.
    genesis_validators: list = field(default_factory=list)
    genesis_validator_stake: Balance = 10_000 * TOKEN
    # Genesis validator CANDIDACIES: bonded (topped up to the genesis
    # stake if needed) and registered via staking.validate, so the
    # credit-weighted election actually rotates the set at era
    # boundaries.  Distinct from genesis_validators: candidates are
    # not seated until an election elects them.
    genesis_candidates: list = field(default_factory=list)
    # Fee market (pallet-transaction-payment role, chain/fees.py):
    # fee = base_fee + weight · fee_per_weight; a block's extrinsics may
    # not exceed block_weight_limit total weight (enforced at authorship
    # AND re-checked at import).  Defaults: ~0.0015 TOKEN for the
    # cheapest call, ~0.026 TOKEN for the heaviest; the limit holds
    # ~200 median calls per block.
    base_fee: Balance = 1_000_000_000
    fee_per_weight: Balance = 10_000_000
    block_weight_limit: int = 100_000
    # Pinned attestation trust anchors (proof/ias.RootStore).  None skips
    # the attestation gate (unit-test pallets in isolation); the node sim
    # always pins a root (reference pins Intel's at
    # primitives/enclave-verify/src/lib.rs:46-93).
    ias_roots: object | None = None


class Runtime:
    def __init__(self, config: RuntimeConfig | None = None) -> None:
        self.config = config or RuntimeConfig()
        cfg = self.config
        self.state = ChainState()
        self.state.randomness = cfg.genesis_randomness

        # Pallet graph, wired as the reference runtime binds the traits
        # (runtime/src/lib.rs:944-1122).
        self.sminer = SminerPallet(self.state, cfg.one_day_block)
        self.storage_handler = StorageHandlerPallet(
            self.state, cfg.one_day_block, cfg.frozen_days, cfg.space_unit_price
        )
        self.oss = OssPallet(self.state)
        self.cacher = CacherPallet(self.state)
        self.scheduler_credit = SchedulerCreditPallet(
            self.state, cfg.credit_period_blocks
        )
        self.staking = StakingPallet(
            self.state, self.sminer, eras_per_year=cfg.eras_per_year
        )
        cert_verifier = None
        if cfg.ias_roots is not None:
            from ..proof import ias as _ias

            cert_verifier = lambda sign, cert, report, pbk: (  # noqa: E731
                _ias.report_binds_key(report, pbk)
                and _ias.verify_attestation(sign, cert, report, cfg.ias_roots)
            )
        self.tee_worker = TeeWorkerPallet(
            self.state, self.staking, self.scheduler_credit,
            cert_verifier=cert_verifier,
        )
        self.file_bank = FileBankPallet(
            self.state,
            self.sminer,
            self.storage_handler,
            tee_worker=self.tee_worker,
            oss=self.oss,
            one_day_block=cfg.one_day_block,
        )
        self.audit = AuditPallet(
            self.state,
            self.sminer,
            self.file_bank,
            self.tee_worker,
            one_day_block=cfg.one_day_block,
            one_hour_block=cfg.one_hour_block,
            lock_time=cfg.audit_lock_time,
            chunk_count=cfg.podr2_chunk_count,
        )
        self.rrsc = RrscPallet(self.state, self.staking, self.scheduler_credit)
        self.evm = EvmPallet(self.state)
        self.fees = FeesPallet(
            self.state, cfg.base_fee, cfg.fee_per_weight,
            cfg.block_weight_limit,
        )

        # Offences + sessions (im-online/offences/session role,
        # runtime/src/lib.rs:1484-1527): the session clock drives era
        # rotation; the offences pallet sweeps heartbeats at every
        # session end (observer) and applies convictions at era
        # boundaries, just before the election.
        self.offences = OffencesPallet(
            self.state, self.staking, self.scheduler_credit
        )
        s_len, s_per_era = session_plan(
            cfg.era_duration_blocks, cfg.sessions_per_era
        )
        self.session = SessionPallet(
            self.state, self.staking, self.rrsc,
            session_length=s_len, sessions_per_era=s_per_era,
            offences=self.offences,
        )
        self.offences.session = self.session
        self.session.add_observer(self.offences.session_sweep)

        for acc, amount in cfg.endowed.items():
            self.state.balances.mint(acc, amount)

        # Seat the genesis authorities: top up to the genesis stake if the
        # endowment doesn't cover it (genesis injection, not a transfer),
        # bond stash=controller, and seat directly (add_validator keeps
        # them in place until real candidacies elect a replacement set).
        for v in cfg.genesis_validators:
            stake = cfg.genesis_validator_stake
            free = self.state.balances.free(v)
            if free < stake:
                self.state.balances.mint(v, stake - free)
            self.staking.bond(v, v, stake)
            self.staking.add_validator(v)
        # Genesis candidacies: bonded + validate()d so the era-boundary
        # election has a real candidate pool from block 1.
        for c in cfg.genesis_candidates:
            if c not in self.staking.bonded:
                stake = cfg.genesis_validator_stake
                free = self.state.balances.free(c)
                if free < stake:
                    self.state.balances.mint(c, stake - free)
                self.staking.bond(c, c, stake)
            self.staking.validate(c)
        # Session 0's authority set enters the historical record so
        # offence evidence against a genesis authority verifies before
        # the first rotation.
        self.session.record_genesis_set()
        # Genesis authorities are also the audit quorum keys (the
        # session-keys genesis role) so a live chain's offchain workers
        # can vote challenges from block 1 without a harness call.
        if cfg.genesis_validators:
            self.audit.initialize_keys(list(cfg.genesis_validators))

        # Root-dispatchable scheduler agenda targets.
        self._dispatch = {
            ("file_bank", "deal_reassign_miner"): self.file_bank.deal_reassign_miner,
            ("file_bank", "calculate_end"): self.file_bank.calculate_end,
            ("file_bank", "miner_exit"): self.file_bank.miner_exit,
        }

    # ------------------------------------------------------------ block loop

    def _refresh_randomness(self) -> None:
        """Per-block shared randomness — stands in for RRSC
        ParentBlockRandomness (reference: runtime/src/lib.rs:1003)."""
        self.state.randomness = blake2b_256(
            b"rrsc:" + self.state.randomness
            + self.state.block_number.to_bytes(8, "little")
        )

    def next_block(self) -> None:
        self.state.block_number += 1
        now = self.state.block_number
        self._refresh_randomness()

        # on_initialize order mirrors pallet index order in
        # construct_runtime! (runtime/src/lib.rs:1529-1537).
        self.audit.on_initialize(now)
        self.file_bank.on_initialize(now)
        self.scheduler_credit.on_initialize(now)

        # pallet-scheduler agenda.
        for call in self.state.agenda.take_due(now):
            self._dispatch_scheduled(call)

        # Session rotation → offence application → era rotation → RRSC
        # epoch rotation (the session clock ticks sessions_per_era times
        # per era, so the era boundary lands on exactly the same blocks
        # as the pre-session `now % era_duration_blocks == 0` rule; the
        # credit-weighted election still runs only when candidacies
        # exist, so genesis-seeded authority sets stay put in minimal
        # sims).
        self.session.on_initialize(now)

    def _dispatch_scheduled(self, call: ScheduledCall) -> None:
        fn = self._dispatch.get((call.pallet, call.method))
        if fn is None:
            return
        try:
            fn(*call.args)
        except DispatchError:
            # A failed scheduled call is dropped, as in pallet-scheduler.
            pass

    def run_to_block(self, target: int) -> None:
        while self.state.block_number < target:
            self.next_block()

    def run_blocks(self, count: int) -> None:
        self.run_to_block(self.state.block_number + count)
