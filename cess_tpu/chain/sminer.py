"""Storage-miner registry: stake, space ledger, rewards, punishments.

Re-design of the reference sminer pallet (reference:
c-pallets/sminer/src/{lib,types,constants}.rs).  Semantics preserved exactly:

 * miner states: positive / frozen / exit / lock / offline
   (constants.rs:3-11);
 * power = 30% idle + 70% service, floor Perbill math (lib.rs:654-662);
 * collateral limit = BASE_LIMIT * (1 + power // TiB), BASE_LIMIT = 2000
   token (lib.rs:798-804, constants.rs:29);
 * reward orders: each verified audit round mints an order paying 20%
   immediately and 80% over 180 tranches, with a 180-order ring
   (lib.rs:664-722, constants.rs:19-23);
 * punishments move reserved collateral into the reward pot and re-freeze
   under-collateralised miners: idle 10%, service 25%, clear 30/60/100%
   (lib.rs:724-796, constants.rs:25-27).

One deliberate divergence: on a punishment exceeding collateral the reference
zeroes `collaterals` *before* computing `debt = punish - collaterals`
(lib.rs:745-747), recording the full punishment as debt; we record
`punish - original_collateral`, the arithmetic the surrounding code implies.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .state import ChainState
from .types import (
    AccountId,
    Balance,
    BlockNumber,
    DispatchError,
    Perbill,
    TOKEN,
    T_BYTE,
    ensure,
)

MOD = "sminer"

# Miner lifecycle states (reference: sminer/src/constants.rs:3-11).
STATE_POSITIVE = "positive"
STATE_FROZEN = "frozen"
STATE_EXIT = "exit"
STATE_LOCK = "lock"
STATE_OFFLINE = "offline"

FAUCET_VALUE = 10_000_000_000_000_000  # constants.rs:13
IDLE_MUTI = Perbill.from_percent(30)  # constants.rs:15
SERVICE_MUTI = Perbill.from_percent(70)  # constants.rs:16
ISSUE_MUTI = Perbill.from_percent(20)  # constants.rs:17
EACH_SHARE_MUTI = Perbill.from_percent(80)  # constants.rs:18
RELEASE_NUMBER = 180  # constants.rs:19
IDLE_PUNI_MUTI = Perbill.from_percent(10)  # constants.rs:25
SERVICE_PUNI_MUTI = Perbill.from_percent(25)  # constants.rs:27
BASE_LIMIT = 2_000 * TOKEN  # constants.rs:29

REWARD_POT = "pot/sminer"  # PalletId("sminer ").into_account equivalent


@dataclass
class MinerInfo:
    """reference: sminer/src/types.rs:6-17"""

    beneficiary: AccountId
    peer_id: bytes
    collaterals: Balance
    debt: Balance = 0
    state: str = STATE_POSITIVE
    idle_space: int = 0
    service_space: int = 0
    lock_space: int = 0


@dataclass
class RewardOrder:
    """reference: sminer/src/types.rs (RewardOrder)"""

    order_reward: Balance
    each_share: Balance
    award_count: int = 1
    has_issued: bool = True


@dataclass
class RewardInfo:
    total_reward: Balance = 0
    reward_issued: Balance = 0
    currently_available_reward: Balance = 0
    order_list: list[RewardOrder] = field(default_factory=list)


@dataclass
class FaucetRecord:
    last_claim_time: BlockNumber = 0


class SminerPallet:
    def __init__(self, state: ChainState, one_day_block: int) -> None:
        self.state = state
        self.one_day_block = one_day_block
        self.miner_items: dict[AccountId, MinerInfo] = {}
        self.all_miner: list[AccountId] = []
        self.reward_map: dict[AccountId, RewardInfo] = {}
        self.faucet_record: dict[AccountId, FaucetRecord] = {}
        self.currency_reward: Balance = 0

    # ---------------------------------------------------------------- calls

    def regnstk(
        self,
        sender: AccountId,
        beneficiary: AccountId,
        peer_id: bytes,
        staking_val: Balance,
    ) -> None:
        """Register + stake (reference: sminer/src/lib.rs:261-307)."""
        ensure(sender not in self.miner_items, MOD, "AlreadyRegistered")
        self.state.balances.reserve(sender, staking_val)
        self.miner_items[sender] = MinerInfo(
            beneficiary=beneficiary, peer_id=peer_id, collaterals=staking_val
        )
        self.all_miner.append(sender)
        self.reward_map[sender] = RewardInfo()
        self.state.deposit_event(MOD, "Registered", acc=sender, staking_val=staking_val)

    def increase_collateral(self, sender: AccountId, collaterals: Balance) -> None:
        """Top up stake, paying off debt first; may thaw a frozen miner
        (reference: sminer/src/lib.rs:316-360)."""
        miner = self._miner(sender)
        remaining = collaterals
        if miner.debt > 0:
            if miner.debt > collaterals:
                miner.debt -= collaterals
                remaining = 0
            else:
                remaining -= miner.debt
                miner.debt = 0
        self.state.balances.reserve(sender, remaining)
        miner.collaterals += remaining
        if miner.state == STATE_FROZEN:
            limit = self.check_collateral_limit(
                self.calculate_power(miner.idle_space, miner.service_space)
            )
            if miner.collaterals >= limit:
                miner.state = STATE_POSITIVE
        self.state.deposit_event(
            MOD, "IncreaseCollateral", acc=sender, balance=miner.collaterals
        )

    def update_beneficiary(self, sender: AccountId, beneficiary: AccountId) -> None:
        self._miner(sender).beneficiary = beneficiary
        self.state.deposit_event(MOD, "UpdataBeneficiary", acc=sender, new=beneficiary)

    def update_peer_id(self, sender: AccountId, peer_id: bytes) -> None:
        miner = self._miner(sender)
        old = miner.peer_id
        miner.peer_id = peer_id
        self.state.deposit_event(MOD, "UpdataIp", acc=sender, old=old, new=peer_id)

    def receive_reward(self, sender: AccountId) -> None:
        """Claim the currently-available tranche (reference: lib.rs:409-455)."""
        if sender not in self.miner_items:
            return
        miner = self.miner_items[sender]
        ensure(miner.state == STATE_POSITIVE, MOD, "NotpositiveState")
        reward = self.reward_map[sender]
        ensure(reward.currently_available_reward != 0, MOD, "NoReward")
        self.state.balances.transfer(
            REWARD_POT, sender, reward.currently_available_reward
        )
        reward.reward_issued += reward.currently_available_reward
        self.state.deposit_event(
            MOD, "Receive", acc=sender, reward=reward.currently_available_reward
        )
        reward.currently_available_reward = 0

    def faucet_top_up(self, sender: AccountId, award: Balance) -> None:
        self.state.balances.transfer(sender, REWARD_POT, award)
        self.state.deposit_event(MOD, "FaucetTopUpMoney", acc=sender)

    def faucet(self, _sender: AccountId, to: AccountId) -> None:
        """One FAUCET_VALUE draw per account per day (reference:
        lib.rs:479-556 including the first-day edge case)."""
        now = self.state.block_number
        record = self.faucet_record.get(to)
        if record is not None:
            if now >= self.one_day_block:
                ok = record.last_claim_time <= now - self.one_day_block
            else:
                ok = record.last_claim_time <= 0
            if not ok:
                # No event on failure: a failed extrinsic must leave state —
                # including the event stream — untouched.
                raise DispatchError(MOD, "LessThan24Hours")
        self.state.balances.transfer(REWARD_POT, to, FAUCET_VALUE)
        self.faucet_record[to] = FaucetRecord(last_claim_time=now)
        self.state.deposit_event(MOD, "DrawFaucetMoney")

    # ------------------------------------------------------------ internals

    def _miner(self, acc: AccountId) -> MinerInfo:
        miner = self.miner_items.get(acc)
        ensure(miner is not None, MOD, "NotMiner", acc)
        return miner

    @staticmethod
    def calculate_power(idle_space: int, service_space: int) -> int:
        """30% idle + 70% service (reference: lib.rs:654-662)."""
        return SERVICE_MUTI.mul_floor(service_space) + IDLE_MUTI.mul_floor(idle_space)

    @staticmethod
    def check_collateral_limit(power: int) -> Balance:
        """BASE_LIMIT * (1 + power // TiB) (reference: lib.rs:798-804)."""
        return BASE_LIMIT * (1 + power // T_BYTE)

    # -- space ledger (MinerControl, reference: lib.rs:560-652,889-924) --

    def add_miner_idle_space(self, acc: AccountId, increment: int) -> None:
        self._miner(acc).idle_space += increment

    def sub_miner_idle_space(self, acc: AccountId, decrement: int) -> None:
        miner = self._miner(acc)
        if miner.state == STATE_EXIT:
            return
        ensure(miner.idle_space >= decrement, MOD, "Overflow")
        miner.idle_space -= decrement

    def add_miner_service_space(self, acc: AccountId, increment: int) -> None:
        # Silently no-op for deregistered miners (the reference tolerates a
        # missing entry here so restoral completion survives a withdrawn
        # origin miner, sminer/src/lib.rs:609-652).
        miner = self.miner_items.get(acc)
        if miner is None:
            return
        miner.service_space += increment

    def sub_miner_service_space(self, acc: AccountId, decrement: int) -> None:
        miner = self.miner_items.get(acc)
        if miner is None:
            return
        if miner.state == STATE_EXIT:
            return
        ensure(miner.service_space >= decrement, MOD, "Overflow")
        miner.service_space -= decrement

    def lock_space(self, acc: AccountId, space: int) -> None:
        miner = self._miner(acc)
        ensure(miner.idle_space >= space, MOD, "Overflow")
        miner.idle_space -= space
        miner.lock_space += space

    def unlock_space(self, acc: AccountId, space: int) -> None:
        miner = self._miner(acc)
        ensure(miner.lock_space >= space, MOD, "Overflow")
        miner.lock_space -= space
        miner.idle_space += space

    def unlock_space_to_service(self, acc: AccountId, space: int) -> None:
        miner = self._miner(acc)
        ensure(miner.lock_space >= space, MOD, "Overflow")
        miner.lock_space -= space
        miner.service_space += space

    def get_power(self, acc: AccountId) -> tuple[int, int]:
        miner = self._miner(acc)
        return miner.idle_space, miner.service_space

    def get_miner_idle_space(self, acc: AccountId) -> int:
        return self._miner(acc).idle_space

    def miner_is_exist(self, acc: AccountId) -> bool:
        return acc in self.miner_items

    def get_miner_state(self, acc: AccountId) -> str:
        return self._miner(acc).state

    def get_all_miner(self) -> list[AccountId]:
        return list(self.all_miner)

    def get_miner_count(self) -> int:
        return len(self.all_miner)

    def get_reward(self) -> Balance:
        return self.currency_reward

    def is_positive(self, acc: AccountId) -> bool:
        return self._miner(acc).state == STATE_POSITIVE

    def is_lock(self, acc: AccountId) -> bool:
        return self._miner(acc).state == STATE_LOCK

    def update_miner_state(self, acc: AccountId, new_state: str) -> None:
        ensure(
            new_state
            in (STATE_POSITIVE, STATE_FROZEN, STATE_EXIT, STATE_LOCK, STATE_OFFLINE),
            MOD,
            "Unexpected",
            new_state,
        )
        self._miner(acc).state = new_state

    # -- rewards --------------------------------------------------------

    def on_unbalanced(self, amount: Balance) -> None:
        """Era sminer-pool deposit (reference: lib.rs:875-887): mints into
        the reward pot and grows CurrencyReward."""
        self.state.balances.mint(REWARD_POT, amount)
        self.currency_reward += amount
        self.state.deposit_event(MOD, "Deposit", balance=amount)

    def calculate_miner_reward(
        self,
        miner: AccountId,
        total_reward: Balance,
        total_idle_space: int,
        total_service_space: int,
        miner_idle_space: int,
        miner_service_space: int,
    ) -> None:
        """Mint one reward order for a passed audit round
        (reference: lib.rs:664-722): proportional power share, 20% issued now,
        80% split over 180 tranches; every pre-existing unexhausted order
        releases one tranche; the order list is a 180-deep ring."""
        total_power = self.calculate_power(total_idle_space, total_service_space)
        miner_power = self.calculate_power(miner_idle_space, miner_service_space)
        prop = Perbill.from_rational(miner_power, total_power)
        this_round_reward = prop.mul_floor(total_reward)
        each_share = EACH_SHARE_MUTI.mul_floor(this_round_reward) // RELEASE_NUMBER
        issued = ISSUE_MUTI.mul_floor(this_round_reward)

        reward_info = self.reward_map.get(miner)
        ensure(reward_info is not None, MOD, "Unexpected", miner)
        ensure(self.currency_reward >= this_round_reward, MOD, "Overflow")

        for order in reward_info.order_list:
            if order.award_count == RELEASE_NUMBER:
                continue
            reward_info.currently_available_reward += order.each_share
            order.award_count += 1
        if len(reward_info.order_list) == RELEASE_NUMBER:
            reward_info.order_list.pop(0)
        reward_info.currently_available_reward += issued + each_share
        reward_info.total_reward += this_round_reward
        reward_info.order_list.append(
            RewardOrder(order_reward=this_round_reward, each_share=each_share)
        )
        self.currency_reward -= this_round_reward

    # -- punishments ----------------------------------------------------

    def deposit_punish(self, miner_acc: AccountId, punish_amount: Balance) -> None:
        """Move reserved collateral into the reward pot; freeze if the miner
        falls under its collateral limit (reference: lib.rs:724-758)."""
        miner = self._miner(miner_acc)
        if miner.collaterals > punish_amount:
            taken = punish_amount
        else:
            taken = miner.collaterals
            miner.debt += punish_amount - taken
        self.state.balances.unreserve(miner_acc, taken)
        self.state.balances.transfer(miner_acc, REWARD_POT, taken)
        self.currency_reward += taken
        miner.collaterals -= taken

        limit = self.check_collateral_limit(
            self.calculate_power(miner.idle_space, miner.service_space)
        )
        if miner.collaterals < limit:
            miner.state = STATE_FROZEN
        self.state.deposit_event(
            MOD, "Punish", acc=miner_acc, amount=punish_amount, taken=taken
        )

    def idle_punish(
        self, miner: AccountId, idle_space: int, service_space: int
    ) -> None:
        limit = self.check_collateral_limit(
            self.calculate_power(idle_space, service_space)
        )
        self.deposit_punish(miner, IDLE_PUNI_MUTI.mul_floor(limit))

    def service_punish(
        self, miner: AccountId, idle_space: int, service_space: int
    ) -> None:
        limit = self.check_collateral_limit(
            self.calculate_power(idle_space, service_space)
        )
        self.deposit_punish(miner, SERVICE_PUNI_MUTI.mul_floor(limit))

    def clear_punish(
        self, miner: AccountId, level: int, idle_space: int, service_space: int
    ) -> None:
        """Escalating no-show punishment 30%/60%/100% (reference:
        lib.rs:782-796)."""
        limit = self.check_collateral_limit(
            self.calculate_power(idle_space, service_space)
        )
        if level == 1:
            amount = Perbill.from_percent(30).mul_floor(limit)
        elif level == 2:
            amount = Perbill.from_percent(60).mul_floor(limit)
        elif level == 3:
            amount = limit
        else:
            raise DispatchError(MOD, "Unexpected", f"level={level}")
        self.deposit_punish(miner, amount)

    # -- exit -----------------------------------------------------------

    def _sweep_unissued_reward(self, acc: AccountId) -> None:
        reward_info = self.reward_map.get(acc)
        if reward_info is not None:
            self.currency_reward += (
                reward_info.total_reward - reward_info.reward_issued
            )

    def execute_exit(self, acc: AccountId) -> None:
        """reference: lib.rs:843-865 — unissued rewards return to the pool,
        the miner leaves AllMiner and parks in state 'exit'."""
        self._sweep_unissued_reward(acc)
        self.all_miner = [a for a in self.all_miner if a != acc]
        self.reward_map.pop(acc, None)
        self._miner(acc).state = STATE_EXIT

    def force_miner_exit(self, acc: AccountId) -> None:
        """reference: lib.rs:818-840 — same sweep, state 'offline'."""
        self._sweep_unissued_reward(acc)
        self.all_miner = [a for a in self.all_miner if a != acc]
        self.reward_map.pop(acc, None)
        self._miner(acc).state = STATE_OFFLINE

    def withdraw(self, acc: AccountId) -> None:
        """reference: lib.rs:866-872 — unreserve remaining collateral and
        delete the miner."""
        miner = self._miner(acc)
        self.state.balances.unreserve(acc, miner.collaterals)
        del self.miner_items[acc]
