"""RRSC stand-in: credit-weighted validator rotation + slot authorship.

The reference's consensus is RRSC (Random Rotational Selection, a BABE
fork living in the forked substrate — SURVEY.md §2 external components:
`pallet_rrsc`/`cessc-consensus-rrsc`, runtime alias at
runtime/src/lib.rs:1503).  Its two protocol-visible capabilities are:

 * validator selection that folds TEE service reputation into the
   election (the `ValidatorCredits` trait implemented by
   scheduler-credit, c-pallets/scheduler-credit/src/lib.rs:242-251);
 * slot-based block authorship driven by per-epoch randomness (the
   `ParentBlockRandomness` the audit/file-bank pallets also consume,
   runtime/src/lib.rs:1003,1069).

This pallet re-expresses both against the framework's deterministic
block loop: `rotate_epoch` runs the credit-weighted election
(staking.elect × scheduler_credit.credits) and refreshes the epoch
randomness; `slot_author` deterministically draws the block author from
the active set, stake-weighted, from (epoch randomness, slot).  The
draw depends only on on-chain state, so every replica computes the
same author for a slot — node/sync.py's import verification leans on
this (`author == slot_author(block.slot)` evaluated against the parent
state), and node/service.py's wall-clock slot loop turns it into a
live rotating-authorship network; chain/node.py still simulates the
multi-role protocol in-process for tests.
"""

from __future__ import annotations

import hashlib

from .state import ChainState
from .types import AccountId

MOD = "rrsc"


class RrscPallet:
    def __init__(
        self,
        state: ChainState,
        staking,
        scheduler_credit,
        max_validators: int = 100,
    ) -> None:
        self.state = state
        self.staking = staking
        self.scheduler_credit = scheduler_credit
        self.max_validators = max_validators
        self.epoch_index: int = 0
        self.epoch_randomness: bytes = bytes(32)

    # ------------------------------------------------------------ epochs

    def rotate_epoch(self) -> list[AccountId]:
        """Era-boundary rotation: elect the active set with TEE credit
        weights and pin this epoch's randomness."""
        # scheduler_credit.credits() is already stash-keyed (it resolves
        # controller → stash through its SchedulerStashAccountFinder,
        # the runtime/src/impls.rs:30-40 role).
        credits = self.scheduler_credit.credits(self.epoch_index)
        elected = self.staking.elect(
            self.max_validators,
            credits,
            full_credit=self.scheduler_credit.full_credit(),
        )
        self.epoch_index += 1
        self.epoch_randomness = self.state.randomness
        self.state.deposit_event(
            MOD, "NewEpoch", index=self.epoch_index, validators=len(elected)
        )
        return elected

    # ------------------------------------------------------------ slots

    def slot_author(self, slot: int) -> AccountId | None:
        """Stake-weighted deterministic author draw for a slot — the
        rotational-selection stand-in for BABE slot claims.  Every
        validator replica computes the same author from shared state."""
        validators = self.staking.validators
        if not validators:
            return None
        weights = []
        for v in validators:
            ledger = self.staking.ledger.get(v)
            weights.append(ledger.bonded if ledger else 1)
        if not any(weights):
            weights = [1] * len(validators)  # uniform fallback
        total = sum(weights)
        digest = hashlib.blake2b(
            b"rrsc/slot" + self.epoch_randomness + slot.to_bytes(8, "little"),
            digest_size=8,
        ).digest()
        draw = int.from_bytes(digest, "little") % total
        acc = 0
        for v, w in zip(validators, weights):
            acc += w
            if draw < acc:
                return v
        return validators[-1]
