"""RRSC pallet: credit-weighted rotation + VRF epoch randomness.

The reference's consensus is RRSC (Random Rotational Selection, a BABE
fork living in the forked substrate — SURVEY.md §2 external components:
`pallet_rrsc`/`cessc-consensus-rrsc`, runtime alias at
runtime/src/lib.rs:1503).  Its protocol-visible capabilities:

 * validator selection that folds TEE service reputation into the
   election (the `ValidatorCredits` trait implemented by
   scheduler-credit, c-pallets/scheduler-credit/src/lib.rs:242-251);
 * slot-based authorship driven by per-epoch randomness, with each
   block's VRF output accumulated into the NEXT epoch's randomness
   (the `ParentBlockRandomness` feed, runtime/src/lib.rs:1003,1069).

This pallet owns the on-chain consensus state for both:

  `rotate_epoch`      runs the credit-weighted election (staking.elect ×
                      scheduler_credit.credits) and pins the new epoch's
                      randomness from the VRF accumulator;
  `fold_vrf_output`   folds one block's verified VRF output into the
                      accumulator — called by the node service exactly
                      once per block, by author and importer alike, so
                      the accumulator is replicated state (covered by
                      chain/checkpoint.py's state hash and snapshot,
                      blob format v3);
  `slot_author`       the deterministic stake-weighted draw from
                      (epoch randomness, slot) — the SECONDARY-author
                      fallback of the claim ladder
                      (cess_tpu/consensus/engine.py); primary claims
                      are won by the VRF threshold instead.

Runtimes that never fold an output (the in-process protocol sims of
chain/node.py drive the runtime without headers) keep the pre-VRF
behavior: rotation falls back to the parent-block randomness hash
chain, so their determinism contract is unchanged.
"""

from __future__ import annotations

import hashlib

from .state import ChainState
from .types import AccountId

MOD = "rrsc"


class RrscPallet:
    def __init__(
        self,
        state: ChainState,
        staking,
        scheduler_credit,
        max_validators: int = 100,
    ) -> None:
        self.state = state
        self.staking = staking
        self.scheduler_credit = scheduler_credit
        self.max_validators = max_validators
        self.epoch_index: int = 0
        self.epoch_randomness: bytes = bytes(32)
        # VRF output accumulator: every imported block folds its
        # verified output here; the fold count distinguishes "no
        # VRF-bearing blocks this epoch" (hash-chain fallback) from a
        # genuinely accumulated epoch.
        self.vrf_accumulator: bytes = bytes(32)
        self.vrf_fold_count: int = 0

    # ------------------------------------------------------------ epochs

    def rotate_epoch(self) -> list[AccountId]:
        """Era-boundary rotation: elect the active set with TEE credit
        weights and pin this epoch's randomness from the accumulated
        VRF outputs (replacing the pre-VRF hash-chain snapshot; the
        chain remains the fallback for header-less sims)."""
        # scheduler_credit.credits() is already stash-keyed (it resolves
        # controller → stash through its SchedulerStashAccountFinder,
        # the runtime/src/impls.rs:30-40 role).
        credits = self.scheduler_credit.credits(self.epoch_index)
        # chilled candidacies (offences) are skipped inside elect; an
        # election that would seat nobody keeps the previous set —
        # both surfaced in the NewEpoch event so liveness drills can
        # read the rotation's health off the event stream
        chilled = sum(
            1 for c in self.staking.candidates
            if self.staking.is_chilled(c)
        )
        elected = self.staking.elect(
            self.max_validators,
            credits,
            full_credit=self.scheduler_credit.full_credit(),
        )
        self.epoch_index += 1
        if self.vrf_fold_count > 0:
            self.epoch_randomness = hashlib.blake2b(
                b"rrsc/epoch" + self.epoch_index.to_bytes(8, "little")
                + self.vrf_accumulator,
                digest_size=32,
            ).digest()
        else:
            self.epoch_randomness = self.state.randomness
        # chain epochs: the new accumulator starts from the epoch
        # randomness it will feed, so epochs are linked even if a whole
        # epoch somehow passes without a block
        self.vrf_accumulator = self.epoch_randomness
        self.vrf_fold_count = 0
        self.state.deposit_event(
            MOD, "NewEpoch", index=self.epoch_index,
            validators=len(elected), chilled_skipped=chilled,
        )
        return elected

    def fold_vrf_output(self, slot: int, output: bytes) -> None:
        """Accumulate one block's verified VRF output.  Part of the
        deterministic state transition: the author folds before
        executing the block, the importer folds after verifying the
        claim — both before run_blocks, so era-boundary rotations in
        the SAME block already see this output."""
        self.vrf_accumulator = hashlib.blake2b(
            b"rrsc/vrf-fold" + self.vrf_accumulator
            + slot.to_bytes(8, "little") + output,
            digest_size=32,
        ).digest()
        self.vrf_fold_count += 1

    # ------------------------------------------------------------ slots

    def stake_weights(self) -> tuple[list[AccountId], list[int], int]:
        """(validators, bonded weights, total) — the one weight source
        for both the secondary draw and the primary VRF threshold
        (consensus/engine.py), so the two claim rungs can never
        disagree about stake."""
        validators = list(self.staking.validators)
        weights = []
        for v in validators:
            ledger = self.staking.ledger.get(v)
            weights.append(ledger.bonded if ledger else 1)
        if not any(weights):
            weights = [1] * len(validators)  # uniform fallback
        return validators, weights, sum(weights)

    def slot_author(self, slot: int) -> AccountId | None:
        """Stake-weighted deterministic SECONDARY author for a slot —
        the fallback rung of the claim ladder: exactly one validator
        per slot, derived from shared state, so every replica agrees
        and the chain advances even when no primary VRF claim wins."""
        validators, weights, total = self.stake_weights()
        if not validators:
            return None
        digest = hashlib.blake2b(
            b"rrsc/slot" + self.epoch_randomness + slot.to_bytes(8, "little"),
            digest_size=8,
        ).digest()
        draw = int.from_bytes(digest, "little") % total
        acc = 0
        for v, w in zip(validators, weights):
            acc += w
            if draw < acc:
                return v
        return validators[-1]
