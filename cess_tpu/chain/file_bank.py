"""File metadata & lifecycle: deals, fragments→miners, buckets, restoral.

Re-design of the reference file-bank pallet (reference:
c-pallets/file-bank/src/{lib,functions,types,constants}.rs).  The protocol
flow preserved end to end:

  upload_declaration → generate_deal (random miner assignment, space locks,
  scheduled retry) → transfer_report (all assigned miners reported; file
  materialises in state Calculate; idle→service accounting) → calculate_end
  (miner lock→service; file Active)

plus the failure machinery: deal reassignment (≤5 attempts then refund),
filler (idle-space) accounting, restoral-order market for lost fragments,
and the miner exit / forced-exit path with its cooling-off ledger.

Geometry: files arrive pre-erasure-coded as segments of FRAGMENT_COUNT=3
fragments (2 data + 1 parity ⇒ the 1.5× `cal_file_size` factor, reference:
lib.rs:468, runtime/src/lib.rs:1024-1025); the RS math itself lives in
cess_tpu.ops.rs as TPU kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils.hashing import Hash64
from ..utils.rng import ProtocolRng
from .state import ChainState
from .types import (
    AccountId,
    BlockNumber,
    DispatchError,
    FRAGMENT_COUNT,
    FRAGMENT_SIZE,
    SEGMENT_SIZE,
    T_BYTE,
    ensure,
)

MOD = "file_bank"

# reference: c-pallets/file-bank/src/constants.rs:1-4
TRANSFER_RATE = 8_947_849       # bytes a miner is assumed to move per block
CALCULATE_RATE = 67_108_864     # bytes a TEE is assumed to tag per block

# reference: runtime/src/lib.rs:1024-1053
SEGMENT_COUNT_LIMIT = 1000
NAME_MIN_LENGTH = 3
NAME_STR_LIMIT = 63
UPLOAD_FILLER_LIMIT = 10
RESTORAL_ORDER_LIFE = 250
OWNER_LIMIT = 50_000

FILLER_SIZE = FRAGMENT_SIZE  # each idle filler is 8 MiB (lib.rs:830-836)

# FileState (reference: file-bank/src/types.rs FileState)
FILE_ACTIVE = "Active"
FILE_CALCULATE = "Calculate"
FILE_MISSING = "Missing"
FILE_RECOVERY = "Recovery"


# ---------------------------------------------------------------- types


@dataclass
class SegmentList:
    """Declared segment: its hash + FRAGMENT_COUNT fragment hashes
    (reference: types.rs SegmentList)."""

    hash: Hash64
    fragment_list: list[Hash64]


@dataclass
class MinerTaskList:
    miner: AccountId
    fragment_list: list[Hash64] = field(default_factory=list)


@dataclass
class UserBrief:
    user: AccountId
    file_name: str
    bucket_name: str


@dataclass
class DealInfo:
    stage: int
    count: int
    file_size: int
    segment_list: list[SegmentList]
    needed_list: list[SegmentList]
    user: UserBrief
    assigned_miner: list[MinerTaskList]
    share_info: list["SegmentInfo"] = field(default_factory=list)
    complete_list: list[AccountId] = field(default_factory=list)


@dataclass
class FragmentInfo:
    hash: Hash64
    avail: bool
    miner: AccountId


@dataclass
class SegmentInfo:
    hash: Hash64
    fragment_list: list[FragmentInfo] = field(default_factory=list)


@dataclass
class FileInfo:
    segment_list: list[SegmentInfo]
    owner: list[UserBrief]
    file_size: int
    completion: BlockNumber
    stat: str


@dataclass
class FillerInfo:
    block_num: int
    miner_address: AccountId
    filler_hash: Hash64


@dataclass
class UserFileSliceInfo:
    file_hash: Hash64
    file_size: int


@dataclass
class BucketInfo:
    object_list: list[Hash64] = field(default_factory=list)
    authority: list[AccountId] = field(default_factory=list)


@dataclass
class RestoralTargetInfo:
    miner: AccountId
    service_space: int
    restored_space: int
    cooling_block: BlockNumber


@dataclass
class RestoralOrderInfo:
    count: int
    miner: AccountId
    origin_miner: AccountId
    fragment_hash: Hash64
    file_hash: Hash64
    gen_block: BlockNumber
    deadline: BlockNumber


# ---------------------------------------------------------------- pallet


class FileBankPallet:
    """Deal/file/restoral state machine.

    Collaborators (injected, mirroring the reference Config bindings at
    runtime/src/lib.rs:1056-1100): sminer (MinerControl), storage_handler
    (StorageHandle), tee_worker (ScheduleFind), oss (OssFindAuthor).
    """

    def __init__(
        self,
        state: ChainState,
        sminer,
        storage_handler,
        tee_worker=None,
        oss=None,
        one_day_block: int = 14400,
    ) -> None:
        self.state = state
        self.sminer = sminer
        self.storage_handler = storage_handler
        self.tee_worker = tee_worker
        self.oss = oss
        self.one_day_block = one_day_block

        self.deal_map: dict[Hash64, DealInfo] = {}
        self.file: dict[Hash64, FileInfo] = {}
        self.bucket: dict[tuple[AccountId, str], BucketInfo] = {}
        self.user_bucket_list: dict[AccountId, list[str]] = {}
        self.user_hold_file_list: dict[AccountId, list[UserFileSliceInfo]] = {}
        self.filler_map: dict[tuple[AccountId, Hash64], FillerInfo] = {}
        self.pending_replacements: dict[AccountId, int] = {}
        self.restoral_order: dict[Hash64, RestoralOrderInfo] = {}
        self.restoral_target: dict[AccountId, RestoralTargetInfo] = {}
        self.miner_lock: dict[AccountId, BlockNumber] = {}
        self.clear_user_list: list[AccountId] = []

    # ------------------------------------------------------------ hooks

    def on_initialize(self, now: BlockNumber) -> None:
        """Daily lease-expiry sweep, then incremental dead-user cleanup at
        ≤300 files per block (reference: lib.rs:363-433)."""
        if now % self.one_day_block == 0:
            self.clear_user_list = self.storage_handler.frozen_task()
        count = 0
        for acc in list(self.clear_user_list):
            file_list = self.user_hold_file_list.get(acc, [])
            while file_list:
                count += 1
                if count == 300:
                    return
                info = file_list.pop()
                f = self.file.get(info.file_hash)
                if f is None:
                    continue
                try:
                    if len(f.owner) > 1:
                        self.remove_file_owner(info.file_hash, acc, user_clear=False)
                    else:
                        self.remove_file_last_owner(
                            info.file_hash, acc, user_clear=False
                        )
                except DispatchError:
                    pass
            try:
                self.storage_handler.delete_user_space_storage(acc)
            except DispatchError:
                pass
            self.clear_user_list = [a for a in self.clear_user_list if a != acc]
            self.user_hold_file_list.pop(acc, None)
            for key in [k for k in self.bucket if k[0] == acc]:
                del self.bucket[key]
            self.user_bucket_list.pop(acc, None)

    # ------------------------------------------------------------ helpers

    @staticmethod
    def cal_file_size(segments: int) -> int:
        """segments × 24 MiB — the 1.5× redundancy bill (reference:
        functions.rs:299-301)."""
        return segments * (SEGMENT_SIZE * 15 // 10)

    def check_permission(self, operator: AccountId, owner: AccountId) -> bool:
        """Owner or OSS-authorized operator (reference: functions.rs:513-518)."""
        if operator == owner:
            return True
        return self.oss is not None and self.oss.is_authorized(owner, operator)

    @staticmethod
    def check_file_spec(deal_info: list[SegmentList]) -> bool:
        return all(len(s.fragment_list) == FRAGMENT_COUNT for s in deal_info)

    def check_is_file_owner(self, acc: AccountId, file_hash: Hash64) -> bool:
        f = self.file.get(file_hash)
        return f is not None and any(b.user == acc for b in f.owner)

    def generate_random_number(self, seed: int) -> int:
        """Nonzero u32 from (shared randomness, seed) — same retry-while-zero
        shape as the reference (reference: functions.rs:424-443)."""
        counter = 0
        while True:
            rng = ProtocolRng(
                self.state.randomness + b"filbak", domain=seed + counter
            )
            v = rng.u32()
            if v != 0:
                return v
            counter += 1

    # ------------------------------------------------------------ buckets

    @staticmethod
    def check_bucket_name_spec(name: str) -> bool:
        """[a-z0-9.-], no leading/trailing dot, no '..' (reference:
        functions.rs check_bucket_name_spec)."""
        if not 3 <= len(name) <= NAME_STR_LIMIT:
            return False
        allowed = set("abcdefghijklmnopqrstuvwxyz0123456789.-")
        if any(c not in allowed for c in name):
            return False
        if name[0] == "." or name[-1] == "." or ".." in name:
            return False
        return True

    def create_bucket_helper(
        self, user: AccountId, bucket_name: str, file_hash: Hash64 | None
    ) -> None:
        """reference: functions.rs:93-123"""
        ensure(len(bucket_name) >= 3, MOD, "LessMinLength")
        ensure((user, bucket_name) not in self.bucket, MOD, "Existed")
        ensure(self.check_bucket_name_spec(bucket_name), MOD, "SpecError")
        bucket = BucketInfo(authority=[user])
        if file_hash is not None:
            bucket.object_list.append(file_hash)
        self.bucket[(user, bucket_name)] = bucket
        self.user_bucket_list.setdefault(user, []).append(bucket_name)

    def add_file_to_bucket(
        self, user: AccountId, bucket_name: str, file_hash: Hash64
    ) -> None:
        bucket = self.bucket.get((user, bucket_name))
        ensure(bucket is not None, MOD, "NonExistent")
        bucket.object_list.append(file_hash)

    def create_bucket(
        self, sender: AccountId, owner: AccountId, name: str
    ) -> None:
        ensure(self.check_permission(sender, owner), MOD, "NoPermission")
        self.create_bucket_helper(owner, name, None)
        self.state.deposit_event(
            MOD, "CreateBucket", operator=sender, owner=owner, bucket_name=name
        )

    def delete_bucket(
        self, sender: AccountId, owner: AccountId, name: str
    ) -> None:
        """reference: lib.rs:873-921 — deletes the bucket and every contained
        file the owner holds."""
        ensure(self.check_permission(sender, owner), MOD, "NoPermission")
        bucket = self.bucket.get((owner, name))
        ensure(bucket is not None, MOD, "NonExistent")
        for file_hash in list(bucket.object_list):
            f = self.file.get(file_hash)
            ensure(f is not None, MOD, "Unexpected")
            if len(f.owner) > 1:
                self.remove_file_owner(file_hash, owner, user_clear=True)
            else:
                self.remove_file_last_owner(file_hash, owner, user_clear=True)
            self.remove_user_hold_file_list(file_hash, owner)
        del self.bucket[(owner, name)]
        self.user_bucket_list[owner] = [
            n for n in self.user_bucket_list.get(owner, []) if n != name
        ]
        self.state.deposit_event(
            MOD, "DeleteBucket", operator=sender, owner=owner, bucket_name=name
        )

    # ------------------------------------------------------------ upload

    def upload_declaration(
        self,
        sender: AccountId,
        file_hash: Hash64,
        deal_info: list[SegmentList],
        user_brief: UserBrief,
        file_size: int,
    ) -> None:
        """reference: lib.rs:447-496"""
        ensure(self.check_permission(sender, user_brief.user), MOD, "NoPermission")
        ensure(self.check_file_spec(deal_info), MOD, "SpecError")
        ensure(len(deal_info) <= SEGMENT_COUNT_LIMIT, MOD, "SpecError")
        ensure(len(user_brief.file_name) >= NAME_MIN_LENGTH, MOD, "SpecError")
        ensure(len(user_brief.bucket_name) >= NAME_MIN_LENGTH, MOD, "SpecError")
        # Validate the bucket name up front: transfer_report creates the
        # bucket *after* irreversible space accounting, so a name that would
        # fail create_bucket_helper must be rejected at declaration time.
        ensure(
            (user_brief.user, user_brief.bucket_name) in self.bucket
            or self.check_bucket_name_spec(user_brief.bucket_name),
            MOD,
            "SpecError",
        )

        needed_space = self.cal_file_size(len(deal_info))
        ensure(
            self.storage_handler.get_user_avail_space(user_brief.user)
            > needed_space,
            MOD,
            "InsufficientAvailableSpace",
        )

        if file_hash in self.file:
            # Dedup: the network already stores the data; the new owner just
            # pays space and joins the owner list (lib.rs:471-486).
            self.storage_handler.update_user_space(user_brief.user, 1, needed_space)
            if (user_brief.user, user_brief.bucket_name) in self.bucket:
                self.add_file_to_bucket(
                    user_brief.user, user_brief.bucket_name, file_hash
                )
            else:
                self.create_bucket_helper(
                    user_brief.user, user_brief.bucket_name, file_hash
                )
            self.add_user_hold_fileslice(user_brief.user, file_hash, needed_space)
            self.file[file_hash].owner.append(user_brief)
        else:
            self.storage_handler.lock_user_space(user_brief.user, needed_space)
            self.generate_deal(file_hash, deal_info, user_brief, file_size)

        self.state.deposit_event(
            MOD,
            "UploadDeclaration",
            operator=sender,
            owner=user_brief.user,
            deal_hash=file_hash,
        )

    def generate_deal(
        self,
        file_hash: Hash64,
        file_info: list[SegmentList],
        user_brief: UserBrief,
        file_size: int,
    ) -> None:
        """reference: functions.rs:134-163"""
        miner_task_list = self.random_assign_miner(file_info)
        space = self.cal_file_size(len(file_info))
        life = space // TRANSFER_RATE + 1
        self.start_first_task(str(file_hash), file_hash, 1, life)
        self.deal_map[file_hash] = DealInfo(
            stage=1,
            count=0,
            file_size=file_size,
            segment_list=list(file_info),
            needed_list=list(file_info),
            user=user_brief,
            assigned_miner=miner_task_list,
        )

    def start_first_task(
        self, task_id: str, deal_hash: Hash64, count: int, life: int
    ) -> None:
        """Schedule deal_reassign_miner at now + 50·count + life
        (reference: functions.rs:165-181)."""
        at = self.state.block_number + 50 * count + life
        self.state.agenda.schedule_named(
            task_id, at, MOD, "deal_reassign_miner", deal_hash, count, life
        )

    def start_second_task(self, task_id: str, deal_hash: Hash64, life: int) -> None:
        at = self.state.block_number + life
        self.state.agenda.schedule_named(
            task_id, at, MOD, "calculate_end", deal_hash
        )

    def random_assign_miner(
        self, needed_list: list[SegmentList]
    ) -> list[MinerTaskList]:
        """Sample positive miners with enough idle space, then round-robin
        fragments across them and lock the space.  The rejection-loop
        structure follows the reference exactly for deterministic replay
        (reference: functions.rs:201-297)."""
        miner_task_list: list[MinerTaskList] = []
        miner_idle_space_list: list[int] = []
        miner_count = SEGMENT_SIZE * 15 // 10 // FRAGMENT_SIZE  # = 3
        seed = self.state.block_number

        all_miner = self.sminer.get_all_miner()
        total = len(all_miner)
        max_count = miner_count * 5
        cur_count = 0
        total_idle_space = 0

        while True:
            if total == 0:
                break
            index = self.generate_random_number(seed) % total
            seed += 1
            if cur_count == max_count:
                break
            cur_count += 1
            miner = all_miner.pop(index)
            total -= 1
            if not self.sminer.is_positive(miner):
                continue
            cur_space = self.sminer.get_miner_idle_space(miner)
            if cur_space > len(needed_list) * FRAGMENT_SIZE:
                total_idle_space += cur_space
                miner_task_list.append(MinerTaskList(miner=miner))
                miner_idle_space_list.append(cur_space)
            if len(miner_task_list) == miner_count:
                break

        ensure(len(miner_task_list) != 0, MOD, "BugInvalid")
        ensure(
            total_idle_space > SEGMENT_SIZE * 15 // 10, MOD, "NodesInsufficient"
        )

        for segment_list in needed_list:
            index = 0
            for frag_hash in segment_list.fragment_list:
                while True:
                    temp_index = index % len(miner_task_list)
                    cur_space = miner_idle_space_list[temp_index]
                    if cur_space > (
                        len(miner_task_list[temp_index].fragment_list) + 1
                    ) * FRAGMENT_SIZE:
                        miner_task_list[temp_index].fragment_list.append(frag_hash)
                        break
                    index += 1
                index += 1

        for miner_task in miner_task_list:
            self.sminer.lock_space(
                miner_task.miner, len(miner_task.fragment_list) * FRAGMENT_SIZE
            )
        return miner_task_list

    def deal_reassign_miner(
        self, deal_hash: Hash64, count: int, life: int
    ) -> None:
        """Root/scheduler call: retry assignment ≤5 times, then refund
        (reference: lib.rs:498-538)."""
        deal_info = self.deal_map.get(deal_hash)
        ensure(deal_info is not None, MOD, "NonExistent")
        if count < 5:
            for miner_task in deal_info.assigned_miner:
                self.sminer.unlock_space(
                    miner_task.miner,
                    FRAGMENT_SIZE * len(miner_task.fragment_list),
                )
            deal_info.assigned_miner = []
            try:
                new_assignment = self.random_assign_miner(
                    deal_info.needed_list
                )
            except DispatchError:
                # The reference executes this under #[transactional], so a
                # failed re-assignment rolls back and the deal waits for the
                # next scheduled retry; here the scheduler dispatch would
                # swallow the error and leak the user's locked space, so
                # terminate the deal through the refund path instead.
                self._refund_deal(deal_hash, deal_info)
                return
            deal_info.assigned_miner = new_assignment
            deal_info.complete_list = []
            deal_info.count = count
            self.start_first_task(str(deal_hash), deal_hash, count + 1, life)
        else:
            self._refund_deal(deal_hash, deal_info)

    def _refund_deal(self, deal_hash: Hash64, deal_info) -> None:
        """Abandon a deal: release the user's and miners' locked space and
        drop it (reference: lib.rs:520-536)."""
        needed_space = self.cal_file_size(len(deal_info.segment_list))
        self.storage_handler.unlock_user_space(
            deal_info.user.user, needed_space
        )
        for miner_task in deal_info.assigned_miner:
            self.sminer.unlock_space(
                miner_task.miner,
                FRAGMENT_SIZE * len(miner_task.fragment_list),
            )
        del self.deal_map[deal_hash]

    # ------------------------------------------------------------ storage

    def transfer_report(self, sender: AccountId, deal_hashes: list[Hash64]) -> None:
        """Assigned miner reports its fragments stored; the last report
        completes stage 2 (reference: lib.rs:618-709)."""
        ensure(len(deal_hashes) < 5, MOD, "LengthExceedsLimit")
        failed_list: list[Hash64] = []
        for deal_hash in deal_hashes:
            deal_info = self.deal_map.get(deal_hash)
            if deal_info is None:
                failed_list.append(deal_hash)
                continue
            task_miners = [mt.miner for mt in deal_info.assigned_miner]
            if sender not in task_miners:
                failed_list.append(deal_hash)
                continue
            if sender not in deal_info.complete_list:
                deal_info.complete_list.append(sender)
            if len(deal_info.complete_list) == len(deal_info.assigned_miner):
                deal_info.stage = 2
                self.generate_file(
                    deal_hash,
                    deal_info.segment_list,
                    deal_info.assigned_miner,
                    deal_info.share_info,
                    deal_info.user,
                    FILE_CALCULATE,
                    deal_info.file_size,
                )
                max_task_count = 0
                for miner_task in deal_info.assigned_miner:
                    count = len(miner_task.fragment_list)
                    max_task_count = max(max_task_count, count)
                    # Fragments displace fillers; until the miner reports the
                    # swap, the debt is tracked (lib.rs:666-671).
                    self.pending_replacements[miner_task.miner] = (
                        self.pending_replacements.get(miner_task.miner, 0) + count
                    )
                needed_space = self.cal_file_size(len(deal_info.segment_list))
                self.storage_handler.unlock_and_used_user_space(
                    deal_info.user.user, needed_space
                )
                self.storage_handler.sub_total_idle_space(needed_space)
                self.storage_handler.add_total_service_space(needed_space)
                self.state.agenda.cancel_named(str(deal_hash))
                max_needed_cal_space = max_task_count * FRAGMENT_SIZE
                life = max_needed_cal_space // TRANSFER_RATE + 1
                life += max_needed_cal_space // CALCULATE_RATE + 1
                self.start_second_task(str(deal_hash), deal_hash, life)
                user = deal_info.user
                if (user.user, user.bucket_name) in self.bucket:
                    self.add_file_to_bucket(user.user, user.bucket_name, deal_hash)
                else:
                    self.create_bucket_helper(
                        user.user, user.bucket_name, deal_hash
                    )
                self.add_user_hold_fileslice(user.user, deal_hash, needed_space)
                self.state.deposit_event(
                    MOD, "StorageCompleted", file_hash=deal_hash
                )
        self.state.deposit_event(
            MOD, "TransferReport", acc=sender, failed_list=tuple(failed_list)
        )

    def generate_file(
        self,
        file_hash: Hash64,
        deal_info: list[SegmentList],
        miner_task_list: list[MinerTaskList],
        share_info: list[SegmentInfo],
        user_brief: UserBrief,
        stat: str,
        file_size: int,
    ) -> None:
        """Materialise fragment→miner metadata (reference:
        functions.rs:16-90): fragments are matched to the assigning miner's
        sorted task list; when the miner pool is at the optimal count each
        segment spreads across distinct miners."""
        # Work on copies — the deal keeps its assignment for calculate_end.
        tasks = [
            MinerTaskList(mt.miner, sorted(mt.fragment_list))
            for mt in miner_task_list
        ]
        segment_info_list: list[SegmentInfo] = []
        for segment in deal_info:
            segment_info = SegmentInfo(hash=segment.hash)
            mark_miner: list[AccountId] = []
            shared = next(
                (s for s in share_info if s.hash == segment.hash), None
            )
            if shared is not None:
                segment_info.fragment_list = list(shared.fragment_list)
            else:
                best_count = SEGMENT_SIZE * 15 // 10 // FRAGMENT_SIZE
                flag = best_count == len(tasks)
                for frag_hash in segment.fragment_list:
                    for miner_task in tasks:
                        if flag and miner_task.miner in mark_miner:
                            continue
                        if frag_hash in miner_task.fragment_list:
                            segment_info.fragment_list.append(
                                FragmentInfo(
                                    hash=frag_hash,
                                    avail=True,
                                    miner=miner_task.miner,
                                )
                            )
                            miner_task.fragment_list.remove(frag_hash)
                            mark_miner.append(miner_task.miner)
                            break
            segment_info_list.append(segment_info)

        self.file[file_hash] = FileInfo(
            segment_list=segment_info_list,
            owner=[user_brief],
            file_size=file_size,
            completion=self.state.block_number,
            stat=stat,
        )

    def calculate_end(self, deal_hash: Hash64) -> None:
        """Root/scheduler call (reference: lib.rs:711-738)."""
        deal_info = self.deal_map.get(deal_hash)
        ensure(deal_info is not None, MOD, "NonExistent")
        for miner_task in deal_info.assigned_miner:
            count = len(miner_task.fragment_list)
            self.sminer.unlock_space_to_service(
                miner_task.miner, FRAGMENT_SIZE * count
            )
        f = self.file.get(deal_hash)
        ensure(f is not None, MOD, "BugInvalid")
        f.stat = FILE_ACTIVE
        del self.deal_map[deal_hash]
        self.state.deposit_event(MOD, "CalculateEnd", file_hash=deal_hash)

    # ------------------------------------------------------------ fillers

    def upload_filler(
        self, sender: AccountId, tee_worker: AccountId, filler_list: list[FillerInfo]
    ) -> None:
        """Miner idle-space proof fillers, 8 MiB each (reference:
        lib.rs:804-842)."""
        ensure(len(filler_list) <= UPLOAD_FILLER_LIMIT, MOD, "LengthExceedsLimit")
        if self.tee_worker is not None:
            ensure(
                self.tee_worker.contains_scheduler(tee_worker),
                MOD,
                "ScheduleNonExistent",
            )
        ensure(self.sminer.is_positive(sender), MOD, "NotQualified")
        for filler in filler_list:
            ensure(
                (sender, filler.filler_hash) not in self.filler_map,
                MOD,
                "FileExistent",
            )
        for filler in filler_list:
            self.filler_map[(sender, filler.filler_hash)] = filler
        idle_space = FILLER_SIZE * len(filler_list)
        self.sminer.add_miner_idle_space(sender, idle_space)
        self.storage_handler.add_total_idle_space(idle_space)
        self.state.deposit_event(
            MOD, "FillerUpload", acc=sender, file_size=idle_space
        )

    def delete_filler(self, sender: AccountId, filler_hash: Hash64) -> None:
        """reference: lib.rs:848-874"""
        ensure(self.sminer.is_positive(sender), MOD, "NotQualified")
        ensure((sender, filler_hash) in self.filler_map, MOD, "NonExistent")
        self.sminer.sub_miner_idle_space(sender, FILLER_SIZE)
        self.storage_handler.sub_total_idle_space(FILLER_SIZE)
        del self.filler_map[(sender, filler_hash)]
        self.state.deposit_event(
            MOD, "FillerDelete", acc=sender, filler_hash=filler_hash
        )

    def replace_file_report(self, sender: AccountId, filler: list[Hash64]) -> None:
        """Miner burns fillers displaced by service fragments (reference:
        lib.rs:740-772)."""
        ensure(len(filler) <= 30, MOD, "LengthExceedsLimit")
        pending = self.pending_replacements.get(sender, 0)
        ensure(len(filler) <= pending, MOD, "LengthExceedsLimit")
        count = 0
        for filler_hash in filler:
            if (sender, filler_hash) in self.filler_map:
                count += 1
                del self.filler_map[(sender, filler_hash)]
        self.pending_replacements[sender] = pending - count
        self.state.deposit_event(
            MOD, "ReplaceFiller", acc=sender, filler_list=tuple(filler)
        )

    def clear_filler(self, miner: AccountId) -> None:
        for key in [k for k in self.filler_map if k[0] == miner]:
            del self.filler_map[key]

    # ------------------------------------------------------------ deletion

    def add_user_hold_fileslice(
        self, user: AccountId, file_hash: Hash64, file_size: int
    ) -> None:
        self.user_hold_file_list.setdefault(user, []).append(
            UserFileSliceInfo(file_hash=file_hash, file_size=file_size)
        )

    def remove_user_hold_file_list(self, file_hash: Hash64, acc: AccountId) -> None:
        if acc in self.user_hold_file_list:
            self.user_hold_file_list[acc] = [
                s for s in self.user_hold_file_list[acc] if s.file_hash != file_hash
            ]

    def remove_file_owner(
        self, file_hash: Hash64, acc: AccountId, user_clear: bool
    ) -> None:
        """reference: functions.rs:352-371"""
        f = self.file.get(file_hash)
        ensure(f is not None, MOD, "Overflow")
        for index, brief in enumerate(f.owner):
            if brief.user == acc:
                if user_clear:
                    self.storage_handler.update_user_space(
                        acc, 2, self.cal_file_size(len(f.segment_list))
                    )
                f.owner.pop(index)
                break

    def remove_file_last_owner(
        self, file_hash: Hash64, acc: AccountId, user_clear: bool
    ) -> None:
        """Last owner gone ⇒ fragments die: miners lose service space (or
        their restoral cooldown credits), global service counter drops, the
        file record is removed (reference: functions.rs:374-416)."""
        f = self.file.get(file_hash)
        ensure(f is not None, MOD, "NonExistent")
        total_fragment_dec = 0
        miner_counts: dict[AccountId, int] = {}
        for segment in f.segment_list:
            for fragment in segment.fragment_list:
                total_fragment_dec += 1
                miner_counts[fragment.miner] = miner_counts.get(fragment.miner, 0) + 1
        for miner, count in sorted(miner_counts.items()):
            if miner in self.restoral_target:
                self.update_restoral_target(miner, FRAGMENT_SIZE * count)
            else:
                self.sminer.sub_miner_service_space(miner, FRAGMENT_SIZE * count)
        if user_clear:
            self.storage_handler.update_user_space(
                acc, 2, total_fragment_dec * FRAGMENT_SIZE
            )
        self.storage_handler.sub_total_service_space(
            total_fragment_dec * FRAGMENT_SIZE
        )
        del self.file[file_hash]

    def delete_user_file(self, file_hash: Hash64, acc: AccountId) -> None:
        """reference: functions.rs:303-320"""
        f = self.file.get(file_hash)
        ensure(f is not None, MOD, "NonExistent")
        ensure(f.stat != FILE_CALCULATE, MOD, "Calculate")
        if any(b.user == acc for b in f.owner):
            if len(f.owner) > 1:
                self.remove_file_owner(file_hash, acc, user_clear=True)
            else:
                self.remove_file_last_owner(file_hash, acc, user_clear=True)

    def bucket_remove_file(self, file_hash: Hash64, acc: AccountId) -> None:
        f = self.file.get(file_hash)
        briefs = [] if f is None else f.owner
        for brief in briefs:
            if brief.user == acc:
                bucket = self.bucket.get((acc, brief.bucket_name))
                ensure(bucket is not None, MOD, "NonExistent")
                bucket.object_list = [
                    h for h in bucket.object_list if h != file_hash
                ]

    def delete_file(
        self, sender: AccountId, owner: AccountId, file_hash_list: list[Hash64]
    ) -> None:
        """reference: lib.rs:773-792"""
        ensure(self.check_permission(sender, owner), MOD, "NoPermission")
        ensure(len(file_hash_list) < 10, MOD, "LengthExceedsLimit")
        for file_hash in file_hash_list:
            ensure(file_hash in self.file, MOD, "NonExistent")
            # bucket_remove_file must read the owner brief before deletion.
            self.bucket_remove_file(file_hash, owner)
            self.delete_user_file(file_hash, owner)
            self.remove_user_hold_file_list(file_hash, owner)
        self.state.deposit_event(
            MOD,
            "DeleteFile",
            operator=sender,
            owner=owner,
            file_hash_list=tuple(file_hash_list),
        )

    def ownership_transfer(
        self, sender: AccountId, target_brief: UserBrief, file_hash: Hash64
    ) -> None:
        """reference: lib.rs:557-608"""
        f = self.file.get(file_hash)
        ensure(f is not None, MOD, "FileNonExistent")
        ensure(self.check_is_file_owner(sender, file_hash), MOD, "NotOwner")
        ensure(
            not self.check_is_file_owner(target_brief.user, file_hash),
            MOD,
            "IsOwned",
        )
        ensure(f.stat == FILE_ACTIVE, MOD, "Unprepared")
        ensure(
            (target_brief.user, target_brief.bucket_name) in self.bucket,
            MOD,
            "NonExistent",
        )
        file_size = self.cal_file_size(len(f.segment_list))
        self.storage_handler.update_user_space(target_brief.user, 1, file_size)
        f.owner.append(target_brief)
        self.add_file_to_bucket(
            target_brief.user, target_brief.bucket_name, file_hash
        )
        self.add_user_hold_fileslice(target_brief.user, file_hash, file_size)
        self.bucket_remove_file(file_hash, sender)
        self.delete_user_file(file_hash, sender)
        self.remove_user_hold_file_list(file_hash, sender)

    # ------------------------------------------------------------ restoral

    def generate_restoral_order(
        self, sender: AccountId, file_hash: Hash64, restoral_fragment: Hash64
    ) -> None:
        """A miner admits fragment loss and opens an order against itself
        (reference: lib.rs:936-980)."""
        ensure(restoral_fragment not in self.restoral_order, MOD, "Existed")
        f = self.file.get(file_hash)
        ensure(f is not None, MOD, "NonExistent")
        for segment in f.segment_list:
            for fragment in segment.fragment_list:
                if fragment.hash == restoral_fragment and fragment.miner == sender:
                    fragment.avail = False
                    self.restoral_order[restoral_fragment] = RestoralOrderInfo(
                        count=0,
                        miner=sender,
                        origin_miner=sender,
                        file_hash=file_hash,
                        fragment_hash=restoral_fragment,
                        gen_block=self.state.block_number,
                        deadline=0,
                    )
                    self.state.deposit_event(
                        MOD,
                        "GenerateRestoralOrder",
                        miner=sender,
                        fragment_hash=restoral_fragment,
                    )
                    return
        raise DispatchError(MOD, "SpecError")

    def claim_restoral_order(
        self, sender: AccountId, restoral_fragment: Hash64
    ) -> None:
        """Any positive miner claims an expired/unclaimed order
        (reference: lib.rs:985-1012)."""
        ensure(self.sminer.is_positive(sender), MOD, "MinerStateError")
        now = self.state.block_number
        order = self.restoral_order.get(restoral_fragment)
        ensure(order is not None, MOD, "NonExistent")
        ensure(now > order.deadline, MOD, "SpecError")
        order.count += 1
        order.deadline = now + RESTORAL_ORDER_LIFE
        order.miner = sender
        self.state.deposit_event(
            MOD, "ClaimRestoralOrder", miner=sender, order_id=restoral_fragment
        )

    def claim_restoral_noexist_order(
        self,
        sender: AccountId,
        miner: AccountId,
        file_hash: Hash64,
        restoral_fragment: Hash64,
    ) -> None:
        """Claim restoral of a fragment whose holder exited (holder must be
        in the RestoralTarget ledger; reference: lib.rs:1014-1070)."""
        ensure(self.sminer.is_positive(sender), MOD, "MinerStateError")
        ensure(restoral_fragment not in self.restoral_order, MOD, "Existed")
        ensure(miner in self.restoral_target, MOD, "NonExistent")
        f = self.file.get(file_hash)
        ensure(f is not None, MOD, "NonExistent")
        for segment in f.segment_list:
            for fragment in segment.fragment_list:
                if fragment.hash == restoral_fragment and fragment.miner == miner:
                    now = self.state.block_number
                    fragment.avail = False
                    self.restoral_order[restoral_fragment] = RestoralOrderInfo(
                        count=0,
                        miner=sender,
                        origin_miner=fragment.miner,
                        file_hash=file_hash,
                        fragment_hash=restoral_fragment,
                        gen_block=now,
                        deadline=now + RESTORAL_ORDER_LIFE,
                    )
                    self.state.deposit_event(
                        MOD,
                        "ClaimRestoralOrder",
                        miner=sender,
                        order_id=restoral_fragment,
                    )
                    return
        raise DispatchError(MOD, "SpecError")

    def restoral_order_complete(
        self, sender: AccountId, fragment_hash: Hash64
    ) -> None:
        """Claimant proves recovery before the deadline; service space moves
        from the origin miner to the claimant (reference: lib.rs:1072-1125)."""
        ensure(self.sminer.is_positive(sender), MOD, "MinerStateError")
        order = self.restoral_order.get(fragment_hash)
        ensure(order is not None, MOD, "NonExistent")
        ensure(order.miner == sender, MOD, "SpecError")
        now = self.state.block_number
        ensure(now < order.deadline, MOD, "Expired")
        f = self.file.get(order.file_hash)
        if f is None:
            del self.restoral_order[fragment_hash]
            return
        for segment in f.segment_list:
            for fragment in segment.fragment_list:
                if (
                    fragment.hash == fragment_hash
                    and fragment.miner == order.origin_miner
                ):
                    self.sminer.sub_miner_service_space(
                        fragment.miner, FRAGMENT_SIZE
                    )
                    self.sminer.add_miner_service_space(sender, FRAGMENT_SIZE)
                    if fragment.miner in self.restoral_target:
                        self.update_restoral_target(fragment.miner, FRAGMENT_SIZE)
                    fragment.avail = True
                    fragment.miner = sender
                    break
        del self.restoral_order[fragment_hash]
        self.state.deposit_event(
            MOD, "RecoveryCompleted", miner=sender, order_id=fragment_hash
        )

    def create_restoral_target(self, miner: AccountId, service_space: int) -> None:
        """Exit cooldown: (service_space // TiB + 1) days (reference:
        functions.rs:540-566)."""
        blocks = (service_space // T_BYTE + 1) * self.one_day_block
        self.restoral_target[miner] = RestoralTargetInfo(
            miner=miner,
            service_space=service_space,
            restored_space=0,
            cooling_block=self.state.block_number + blocks,
        )

    def update_restoral_target(self, miner: AccountId, space: int) -> None:
        info = self.restoral_target.get(miner)
        ensure(info is not None, MOD, "NonExistent")
        info.restored_space += space

    # ------------------------------------------------------------ miner exit

    def miner_exit_prep(self, sender: AccountId) -> None:
        """reference: lib.rs:1128-1164"""
        if sender in self.miner_lock:
            ensure(
                self.state.block_number > self.miner_lock[sender],
                MOD,
                "MinerStateError",
            )
        ensure(self.sminer.is_positive(sender), MOD, "MinerStateError")
        self.sminer.update_miner_state(sender, "lock")
        lock_time = self.state.block_number + self.one_day_block
        self.miner_lock[sender] = lock_time
        self.state.agenda.schedule_named(
            f"exit:{sender}", lock_time, MOD, "miner_exit", sender
        )
        self.state.deposit_event(MOD, "MinerExitPrep", miner=sender)

    def miner_exit(self, miner: AccountId) -> None:
        """Root/scheduler call (reference: lib.rs:1168-1190)."""
        ensure(self.sminer.is_lock(miner), MOD, "MinerStateError")
        self.clear_filler(miner)
        idle_space, service_space = self.sminer.get_power(miner)
        self.storage_handler.sub_total_idle_space(idle_space)
        self.sminer.execute_exit(miner)
        self.create_restoral_target(miner, service_space)

    def miner_withdraw(self, sender: AccountId) -> None:
        """reference: lib.rs:1192-1212"""
        info = self.restoral_target.get(sender)
        ensure(info is not None, MOD, "MinerStateError")
        now = self.state.block_number
        if now < info.cooling_block and info.restored_space != info.service_space:
            raise DispatchError(MOD, "MinerStateError")
        self.sminer.withdraw(sender)
        self.state.deposit_event(MOD, "Withdraw", acc=sender)

    # -- RandomFileList trait surface used by audit (reference:
    # file-bank/src/lib.rs:1216-1226, functions.rs:527-538) --------------

    def force_miner_exit(self, miner: AccountId) -> None:
        self.clear_filler(miner)
        idle_space, service_space = self.sminer.get_power(miner)
        self.storage_handler.sub_total_idle_space(idle_space)
        self.sminer.force_miner_exit(miner)
        self.create_restoral_target(miner, service_space)
