"""CESS-customized staking economics: era reward pools + scheduler slashing.

The reference forks pallet-staking wholesale (c-pallets/staking, 14.7k LoC);
what CESS actually changed — and what this module re-designs — is:

 * fixed first-year reward pools split validator/sminer (238.5M / 477M
   token), decaying ×0.841 per year for 30 years, divided evenly across the
   eras of a year (reference: c-pallets/staking/src/pallet/impls.rs:432-475,
   runtime/src/lib.rs:586-589);
 * the sminer share is minted into the sminer reward pot via OnUnbalanced
   (reference: c-pallets/sminer/src/lib.rs:875-887);
 * `slash_scheduler`: a misbehaving TEE's stash loses 5% of
   MinValidatorBond (reference: c-pallets/staking/src/slashing.rs:693-706).

NPoS election, nominations and bags-list are host-framework consensus
machinery out of scope for the storage protocol; the bonded (stash →
controller) registry and validator set are kept, since tee-worker
registration and the audit quorum depend on them.
"""

from __future__ import annotations

from dataclasses import dataclass

from .state import ChainState
from .types import AccountId, Balance, Perbill, TOKEN, ensure

MOD = "staking"

TREASURY_POT = "pot/treasury"

# reference: runtime/src/lib.rs:586-589
FIRST_YEAR_VALIDATOR_REWARDS = 238_500_000 * TOKEN
FIRST_YEAR_SMINER_REWARDS = 477_000_000 * TOKEN
REWARD_DECREASE_RATIO = Perbill(841_000_000)  # from_perthousand(841)
REWARD_DECREASE_YEARS = 30


@dataclass
class Ledger:
    stash: AccountId
    controller: AccountId
    bonded: Balance


class StakingPallet:
    def __init__(
        self,
        state: ChainState,
        sminer,
        eras_per_year: int = 1460,
        min_validator_bond: Balance = 5_000 * TOKEN,
    ) -> None:
        self.state = state
        self.sminer = sminer
        self.eras_per_year = eras_per_year
        self.min_validator_bond = min_validator_bond
        self.bonded: dict[AccountId, AccountId] = {}  # stash -> controller
        self.ledger: dict[AccountId, Ledger] = {}  # stash -> ledger
        self.validators: list[AccountId] = []  # stash accounts
        self.active_era: int = 0
        self.eras_validator_reward: dict[int, Balance] = {}

    # -- bonding ---------------------------------------------------------

    def bond(self, stash: AccountId, controller: AccountId, value: Balance) -> None:
        ensure(stash not in self.bonded, MOD, "AlreadyBonded")
        self.state.balances.reserve(stash, value)
        self.bonded[stash] = controller
        self.ledger[stash] = Ledger(stash, controller, value)
        self.state.deposit_event(MOD, "Bonded", stash=stash, amount=value)

    def bonded_controller(self, stash: AccountId) -> AccountId | None:
        return self.bonded.get(stash)

    def add_validator(self, stash: AccountId) -> None:
        ensure(stash in self.bonded, MOD, "NotStash")
        if stash not in self.validators:
            self.validators.append(stash)

    # -- era economics ----------------------------------------------------

    def rewards_in_era(self, active_era_index: int) -> tuple[Balance, Balance]:
        """(validator_payout, sminer_payout) for one era (reference:
        impls.rs:454-475): yearly pools decay ×0.841 for ≤30 years, then
        flatten; each era gets 1/eras_per_year of the year's pool."""
        year_num = min(active_era_index // self.eras_per_year, REWARD_DECREASE_YEARS)
        validator_rewards = FIRST_YEAR_VALIDATOR_REWARDS
        sminer_rewards = FIRST_YEAR_SMINER_REWARDS
        for _ in range(year_num):
            validator_rewards = REWARD_DECREASE_RATIO.mul_floor(validator_rewards)
            sminer_rewards = REWARD_DECREASE_RATIO.mul_floor(sminer_rewards)
        return (
            validator_rewards // self.eras_per_year,
            sminer_rewards // self.eras_per_year,
        )

    def end_era(self) -> None:
        """reference: impls.rs:432-451 — record the validator pool and mint
        the sminer pool into the sminer reward pot."""
        validator_payout, sminer_payout = self.rewards_in_era(self.active_era)
        self.state.deposit_event(
            MOD,
            "EraPaid",
            era_index=self.active_era,
            validator_payout=validator_payout,
            remainder=sminer_payout,
        )
        self.eras_validator_reward[self.active_era] = validator_payout
        self.sminer.on_unbalanced(sminer_payout)
        self.active_era += 1

    # -- slashing ----------------------------------------------------------

    def slash_scheduler(self, stash: AccountId) -> None:
        """5% of MinValidatorBond off the TEE's stash, to treasury
        (reference: slashing.rs:693-706)."""
        amount = Perbill.from_percent(5).mul_floor(self.min_validator_bond)
        ledger = self.ledger.get(stash)
        if ledger is None:
            return
        taken = min(ledger.bonded, amount)
        ledger.bonded -= taken
        self.state.balances.unreserve(stash, taken)
        self.state.balances.transfer(stash, TREASURY_POT, taken)
        self.state.deposit_event(MOD, "Slashed", staker=stash, amount=taken)
