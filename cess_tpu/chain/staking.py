"""CESS-customized staking economics: era reward pools + scheduler slashing.

The reference forks pallet-staking wholesale (c-pallets/staking, 14.7k LoC);
what CESS actually changed — and what this module re-designs — is:

 * fixed first-year reward pools split validator/sminer (238.5M / 477M
   token), decaying ×0.841 per year for 30 years, divided evenly across the
   eras of a year (reference: c-pallets/staking/src/pallet/impls.rs:432-475,
   runtime/src/lib.rs:586-589);
 * the sminer share is minted into the sminer reward pot via OnUnbalanced
   (reference: c-pallets/sminer/src/lib.rs:875-887);
 * `slash_scheduler`: a misbehaving TEE's stash loses 5% of
   MinValidatorBond (reference: c-pallets/staking/src/slashing.rs:693-706).

NPoS election, nominations and bags-list are host-framework consensus
machinery out of scope for the storage protocol; the bonded (stash →
controller) registry and validator set are kept, since tee-worker
registration and the audit quorum depend on them.
"""

from __future__ import annotations

from dataclasses import dataclass

from .state import ChainState
from .types import AccountId, Balance, Perbill, TOKEN, ensure

MOD = "staking"

TREASURY_POT = "pot/treasury"

# reference: runtime/src/lib.rs:586-589
FIRST_YEAR_VALIDATOR_REWARDS = 238_500_000 * TOKEN
FIRST_YEAR_SMINER_REWARDS = 477_000_000 * TOKEN
REWARD_DECREASE_RATIO = Perbill(841_000_000)  # from_perthousand(841)
REWARD_DECREASE_YEARS = 30


# Unbonded funds stay locked for this many eras before withdrawal (the
# stock pallet-staking BondingDuration the fork keeps).
BONDING_DURATION_ERAS = 28

# Reward/backing records older than this are pruned at era end (the
# stock HistoryDepth role): unclaimed payouts expire, state stays bounded.
HISTORY_DEPTH_ERAS = 84

# Election weight cap per candidate, as a multiple of MinValidatorBond
# (the MaxExposure role): one whale's backing cannot dominate the
# credit-weighted score beyond this.  Election-only — payouts still
# distribute over the REAL backing.
MAX_BACKING_BONDS = 256


@dataclass
class UnlockChunk:
    value: Balance
    era: int  # first era the chunk can be withdrawn in


@dataclass
class Ledger:
    stash: AccountId
    controller: AccountId
    bonded: Balance
    unlocking: list = None  # list[UnlockChunk]

    def __post_init__(self):
        if self.unlocking is None:
            self.unlocking = []


class StakingPallet:
    def __init__(
        self,
        state: ChainState,
        sminer,
        eras_per_year: int = 1460,
        min_validator_bond: Balance = 5_000 * TOKEN,
    ) -> None:
        self.state = state
        self.sminer = sminer
        self.eras_per_year = eras_per_year
        self.min_validator_bond = min_validator_bond
        self.max_candidate_backing = MAX_BACKING_BONDS * min_validator_bond
        self.bonded: dict[AccountId, AccountId] = {}  # stash -> controller
        self.ledger: dict[AccountId, Ledger] = {}  # stash -> ledger
        self.validators: list[AccountId] = []  # ACTIVE set (stash accounts)
        self.candidates: list[AccountId] = []  # validator candidacies
        self.nominations: dict[AccountId, list[AccountId]] = {}
        # stash → first era it may validate again (offences chill; the
        # election and `validate` both skip stashes still inside it)
        self.chilled_until: dict[AccountId, int] = {}
        self.active_era: int = 0
        self.eras_validator_reward: dict[int, Balance] = {}
        self.era_backing: dict[int, dict[AccountId, dict[AccountId, Balance]]] = {}
        self.payout_claimed: set[tuple[int, AccountId]] = set()

    # -- bonding ---------------------------------------------------------

    def bond(self, stash: AccountId, controller: AccountId, value: Balance) -> None:
        ensure(stash not in self.bonded, MOD, "AlreadyBonded")
        self.state.balances.reserve(stash, value)
        self.bonded[stash] = controller
        self.ledger[stash] = Ledger(stash, controller, value)
        self.state.deposit_event(MOD, "Bonded", stash=stash, amount=value)

    def bonded_controller(self, stash: AccountId) -> AccountId | None:
        return self.bonded.get(stash)

    def bond_extra(self, stash: AccountId, value: Balance) -> None:
        ledger = self.ledger.get(stash)
        ensure(ledger is not None, MOD, "NotStash")
        self.state.balances.reserve(stash, value)
        ledger.bonded += value
        self.state.deposit_event(MOD, "Bonded", stash=stash, amount=value)

    def unbond(self, stash: AccountId, value: Balance) -> None:
        """Schedule `value` for unlock BONDING_DURATION eras out (stock
        pallet-staking unbond shape the fork keeps)."""
        ledger = self.ledger.get(stash)
        ensure(ledger is not None, MOD, "NotStash")
        ensure(0 < value <= ledger.bonded, MOD, "InsufficientBond")
        ledger.bonded -= value
        ledger.unlocking.append(
            UnlockChunk(value, self.active_era + BONDING_DURATION_ERAS)
        )
        if (
            stash in self.candidates
            and ledger.bonded < self.min_validator_bond
        ):
            self.chill(stash)
        self.state.deposit_event(MOD, "Unbonded", stash=stash, amount=value)

    def withdraw_unbonded(self, stash: AccountId) -> Balance:
        """Release every chunk whose era has arrived; returns the amount.
        A fully-empty ledger is reaped (stash can re-bond afresh)."""
        ledger = self.ledger.get(stash)
        ensure(ledger is not None, MOD, "NotStash")
        due = [c for c in ledger.unlocking if c.era <= self.active_era]
        ledger.unlocking = [
            c for c in ledger.unlocking if c.era > self.active_era
        ]
        amount = sum(c.value for c in due)
        if amount:
            self.state.balances.unreserve(stash, amount)
            self.state.deposit_event(
                MOD, "Withdrawn", stash=stash, amount=amount
            )
        if ledger.bonded == 0 and not ledger.unlocking:
            del self.ledger[stash]
            del self.bonded[stash]
            self.nominations.pop(stash, None)
            if stash in self.candidates:
                self.candidates.remove(stash)
            if stash in self.validators:
                self.validators.remove(stash)
        return amount

    # -- intentions -------------------------------------------------------

    def validate(self, stash: AccountId) -> None:
        """Declare validator candidacy (stock `validate`).  A stash
        still inside an offences chill must sit the chill out before
        re-declaring."""
        ledger = self.ledger.get(stash)
        ensure(ledger is not None, MOD, "NotStash")
        ensure(
            ledger.bonded >= self.min_validator_bond, MOD, "InsufficientBond"
        )
        ensure(not self.is_chilled(stash), MOD, "Chilled")
        if stash not in self.candidates:
            self.candidates.append(stash)
            self.state.deposit_event(MOD, "ValidatorPrefsSet", stash=stash)

    def nominate(self, stash: AccountId, targets: list[AccountId]) -> None:
        ensure(stash in self.ledger, MOD, "NotStash")
        ensure(targets, MOD, "EmptyTargets")
        ensure(
            all(t in self.candidates for t in targets), MOD, "BadTarget"
        )
        self.nominations[stash] = list(dict.fromkeys(targets))
        self.state.deposit_event(
            MOD, "Nominated", stash=stash,
            targets=tuple(self.nominations[stash]),
        )

    def chill(self, stash: AccountId) -> None:
        if stash in self.candidates:
            self.candidates.remove(stash)
            self.state.deposit_event(MOD, "Chilled", stash=stash)
        self.nominations.pop(stash, None)

    def is_chilled(self, stash: AccountId) -> bool:
        return self.active_era < self.chilled_until.get(stash, 0)

    def force_chill(self, stash: AccountId, until_era: int) -> None:
        """Offences-driven chill: drop the candidacy AND refuse
        re-candidacy until `until_era` (the DisableStrategy role —
        chill() alone lets the offender `validate` right back in)."""
        self.chill(stash)
        self.chilled_until[stash] = max(
            self.chilled_until.get(stash, 0), until_era
        )
        self.state.deposit_event(
            MOD, "Chilled", stash=stash, until_era=until_era
        )

    def add_validator(self, stash: AccountId) -> None:
        """Directly seat a validator (genesis/authority injection).  Does
        NOT register candidacy: a directly-seated authority stays put
        until real candidacies exist and an election replaces the set."""
        ensure(stash in self.bonded, MOD, "NotStash")
        if stash not in self.validators:
            self.validators.append(stash)

    # -- election ---------------------------------------------------------

    def backing_of(self, stash: AccountId) -> dict[AccountId, Balance]:
        """who-backs-whom for one candidate: own bond + nominations."""
        out: dict[AccountId, Balance] = {}
        ledger = self.ledger.get(stash)
        if ledger is not None and ledger.bonded:
            out[stash] = ledger.bonded
        for nom, targets in self.nominations.items():
            if stash in targets:
                nl = self.ledger.get(nom)
                if nl is not None and nl.bonded:
                    out[nom] = out.get(nom, 0) + nl.bonded // len(targets)
        return out

    def _all_backings(self) -> dict[AccountId, dict[AccountId, Balance]]:
        """who-backs-whom for EVERY candidate in one pass: O(candidates
        + nominations) instead of backing_of's O(candidates ×
        nominations) — the part of the election that must stay cheap at
        thousands of candidates."""
        out: dict[AccountId, dict[AccountId, Balance]] = {}
        for stash in self.candidates:
            backing: dict[AccountId, Balance] = {}
            ledger = self.ledger.get(stash)
            if ledger is not None and ledger.bonded:
                backing[stash] = ledger.bonded
            out[stash] = backing
        for nom, targets in self.nominations.items():
            nl = self.ledger.get(nom)
            if nl is None or not nl.bonded:
                continue
            share = nl.bonded // len(targets)
            if not share:
                continue
            for target in targets:
                backing = out.get(target)
                if backing is not None:
                    backing[nom] = backing.get(nom, 0) + share
        return out

    def elect(
        self, max_validators: int, credits: dict[AccountId, int] | None = None,
        full_credit: int = 1000,
    ) -> list[AccountId]:
        """Credit-weighted validator selection — the RRSC/ValidatorCredits
        role (reference: the forked consensus consumes
        scheduler-credit's ValidatorCredits impl,
        c-pallets/scheduler-credit/src/lib.rs:242-251): each candidate's
        total backing — CAPPED at max_candidate_backing so one whale
        cannot own the set — is scaled by (full + credit)/full, so TEE
        service reputation tilts the election.  Deterministic: ties
        break on the account id.

        Bags-shaped (the bags-list role of the reference's election
        provider): candidates are bucketed into exponential score bags
        (bag b holds scores in [2^(b-1), 2^b), so every member of a
        higher bag outranks every member of a lower one) and only the
        bags actually needed to fill the set are sorted — placement is
        O(candidates), sorting is bounded by the consumed bags, and the
        result is bit-identical to a full global sort.  Chilled stashes
        (offences) are skipped outright."""
        credits = credits or {}
        backings = self._all_backings()
        bags: dict[int, list[tuple[int, AccountId]]] = {}
        for stash in self.candidates:
            if self.is_chilled(stash):
                continue
            ledger = self.ledger.get(stash)
            if ledger is None or ledger.bonded < self.min_validator_bond:
                continue
            backing = min(
                sum(backings[stash].values()), self.max_candidate_backing
            )
            weight = full_credit + credits.get(stash, 0)
            score = backing * weight // full_credit
            bags.setdefault(score.bit_length(), []).append((score, stash))
        elected: list[AccountId] = []
        for bag in sorted(bags, reverse=True):
            if len(elected) >= max_validators:
                break
            for score, stash in sorted(
                bags[bag], key=lambda t: (-t[0], t[1])
            ):
                elected.append(stash)
                if len(elected) >= max_validators:
                    break
        if not elected:
            # Never seat an empty authority set: a chain whose every
            # candidate is chilled or under-bonded keeps its previous
            # validators (liveness over rotation).  They still earn:
            # record their live backing for this era so payout_stakers
            # can distribute the era pool to the set that actually
            # validated it.
            self.era_backing[self.active_era] = {
                s: self.backing_of(s) for s in self.validators
            }
            return list(self.validators)
        self.validators = elected
        self.era_backing[self.active_era] = {s: backings[s] for s in elected}
        return elected

    # -- payout -----------------------------------------------------------

    def payout_stakers(self, era: int, stash: AccountId) -> Balance:
        """Pay one validator's era share, split pro-rata over its backers
        (stock payout_stakers shape, commission 0).  The era pool divides
        across the elected set by backing weight."""
        ensure((era, stash) not in self.payout_claimed, MOD, "AlreadyClaimed")
        pool = self.eras_validator_reward.get(era)
        ensure(pool is not None, MOD, "InvalidEraToReward")
        backing = self.era_backing.get(era, {})
        ensure(stash in backing, MOD, "NotElected")
        total_all = sum(sum(b.values()) for b in backing.values())
        mine = backing[stash]
        total_mine = sum(mine.values())
        if total_all == 0 or total_mine == 0:
            return 0
        share = pool * total_mine // total_all
        paid = 0
        for backer, amount in sorted(mine.items()):
            cut = share * amount // total_mine
            if cut:
                self.state.balances.mint(backer, cut)
                paid += cut
        self.payout_claimed.add((era, stash))
        self.state.deposit_event(
            MOD, "Rewarded", stash=stash, era=era, amount=paid
        )
        return paid

    # -- era economics ----------------------------------------------------

    def rewards_in_era(self, active_era_index: int) -> tuple[Balance, Balance]:
        """(validator_payout, sminer_payout) for one era (reference:
        impls.rs:454-475): yearly pools decay ×0.841 for ≤30 years, then
        flatten; each era gets 1/eras_per_year of the year's pool."""
        year_num = min(active_era_index // self.eras_per_year, REWARD_DECREASE_YEARS)
        validator_rewards = FIRST_YEAR_VALIDATOR_REWARDS
        sminer_rewards = FIRST_YEAR_SMINER_REWARDS
        for _ in range(year_num):
            validator_rewards = REWARD_DECREASE_RATIO.mul_floor(validator_rewards)
            sminer_rewards = REWARD_DECREASE_RATIO.mul_floor(sminer_rewards)
        return (
            validator_rewards // self.eras_per_year,
            sminer_rewards // self.eras_per_year,
        )

    def end_era(self) -> None:
        """reference: impls.rs:432-451 — record the validator pool and mint
        the sminer pool into the sminer reward pot."""
        validator_payout, sminer_payout = self.rewards_in_era(self.active_era)
        self.state.deposit_event(
            MOD,
            "EraPaid",
            era_index=self.active_era,
            validator_payout=validator_payout,
            remainder=sminer_payout,
        )
        self.eras_validator_reward[self.active_era] = validator_payout
        self.sminer.on_unbalanced(sminer_payout)
        self.active_era += 1
        # HistoryDepth pruning: expire stale reward/backing/claim records
        horizon = self.active_era - HISTORY_DEPTH_ERAS
        if horizon >= 0:
            self.eras_validator_reward.pop(horizon, None)
            self.era_backing.pop(horizon, None)
            self.payout_claimed = {
                (era, s) for era, s in self.payout_claimed if era > horizon
            }

    # -- slashing ----------------------------------------------------------

    def slash_scheduler(self, stash: AccountId) -> None:
        """5% of MinValidatorBond off the TEE's stash, to treasury
        (reference: slashing.rs:693-706)."""
        amount = Perbill.from_percent(5).mul_floor(self.min_validator_bond)
        ledger = self.ledger.get(stash)
        if ledger is None:
            return
        taken = min(ledger.bonded, amount)
        ledger.bonded -= taken
        self.state.balances.unreserve(stash, taken)
        self.state.balances.transfer(stash, TREASURY_POT, taken)
        self.state.deposit_event(MOD, "Slashed", staker=stash, amount=taken)

    def slash_offender(self, stash: AccountId, percent: int) -> Balance:
        """Offence slash: `percent`% of the offender's CURRENT bonded
        stake moves from its reserve straight to the treasury pot (the
        offences → staking slashing route, reference:
        slashing.rs + runtime/src/lib.rs:1509).  Unlocking chunks are
        not chased (scope-cut register, docs/offences.md).  Returns
        the amount actually taken."""
        ledger = self.ledger.get(stash)
        if ledger is None:
            return 0
        amount = ledger.bonded * max(0, min(100, percent)) // 100
        taken = self.state.balances.slash_reserved(
            stash, TREASURY_POT, amount
        )
        ledger.bonded -= min(ledger.bonded, taken)
        self.state.deposit_event(MOD, "Slashed", staker=stash, amount=taken)
        return taken
