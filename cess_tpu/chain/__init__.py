"""Protocol state machines (SURVEY.md §7 L3-L4).

Deterministic, replayable re-designs of the reference pallets
(/root/reference/c-pallets/*): every module is a plain-Python state machine
operating on a shared ChainState — no Substrate, no wasm — with the
cryptographic hot paths delegated to the ProofBackend seam (cess_tpu.proof)
so batch work runs on TPU.
"""
