"""Transaction fee market: static per-call weights, a per-block weight
limit, and the 20/80 treasury/author fee split.

Role match: the reference prices every dispatchable with benchmarked
weights (`c-pallets/*/src/weights.rs`) and routes collected fees through
`DealWithFees` — 20% to the treasury pot, 80% to the block author
(reference: runtime/src/impls.rs:9-28, runtime/src/lib.rs:429-441).
Here the weights are a hand-assigned static table (the scope cut is
registered in docs/fees.md): relative cost ORDER matches the reference's
benchmarks (storage-heavy file-bank/audit calls dwarf flag flips like
`oss.authorize`), absolute values are picoseconds-free units chosen so
~100 cheap calls or ~2 heavy ones fill a block.

Determinism contract: fees are charged inside block application (the
node's shared authoring/import path), so every replica debits identical
amounts and the split lands in the state hash.  The per-block
accumulator `block_fees` carries intra-block state between charge() and
distribute() and is always zero at snapshot time — both callers
distribute before hashing.
"""

from __future__ import annotations

from .staking import TREASURY_POT
from .state import ChainState
from .types import Balance, Perbill, ensure

MOD = "fees"

# Escrow pot fees sit in between charge (per extrinsic) and distribute
# (at block commit) — a pot account like the treasury's, never a
# balance sink (distribute always empties it into author + treasury).
FEE_POT = "pot/fees"

# Treasury's cut of every block's fees; the author keeps the rest
# (reference runtime/src/impls.rs:9-28: 20% treasury / 80% author).
TREASURY_CUT = Perbill.from_percent(20)

# ---------------------------------------------------------------- weights
#
# Static weight per (module, call) — the */weights.rs role.  Units are
# abstract "weight points": the default block limit (RuntimeConfig
# .block_weight_limit = 100_000) holds ~2000 `oss.authorize` or ~40
# `audit.submit_verify_result`.  Every entry in the node's
# EXTRINSIC_DISPATCH table MUST have a weight here —
# tests/test_fees.py enforces completeness in both directions.
WEIGHTS: dict[tuple[str, str], int] = {
    # sminer (reference c-pallets/sminer/src/weights.rs)
    ("sminer", "regnstk"): 250,
    ("sminer", "increase_collateral"): 80,
    ("sminer", "update_beneficiary"): 60,
    ("sminer", "update_peer_id"): 60,
    ("sminer", "receive_reward"): 180,
    ("sminer", "faucet_top_up"): 70,
    ("sminer", "faucet"): 70,
    ("sminer", "withdraw"): 120,
    # storage-handler
    ("storage_handler", "buy_space"): 150,
    ("storage_handler", "expansion_space"): 130,
    ("storage_handler", "renewal_space"): 130,
    # oss: flag flips — the cheapest calls on the chain
    ("oss", "authorize"): 50,
    ("oss", "cancel_authorize"): 45,
    ("oss", "register"): 70,
    ("oss", "update"): 55,
    ("oss", "destroy"): 55,
    # cacher
    ("cacher", "logout"): 45,
    # staking
    ("staking", "bond"): 140,
    ("staking", "bond_extra"): 90,
    ("staking", "unbond"): 110,
    ("staking", "withdraw_unbonded"): 110,
    ("staking", "validate"): 100,
    ("staking", "nominate"): 100,
    ("staking", "chill"): 60,
    # tee-worker: register re-verifies an RSA attestation chain
    ("tee_worker", "exit"): 90,
    ("tee_worker", "register"): 800,
    # file-bank: storage-heavy, the reference's priciest user calls
    ("file_bank", "transfer_report"): 300,
    ("file_bank", "replace_file_report"): 250,
    ("file_bank", "delete_file"): 200,
    ("file_bank", "create_bucket"): 80,
    ("file_bank", "delete_bucket"): 90,
    ("file_bank", "generate_restoral_order"): 150,
    ("file_bank", "claim_restoral_order"): 120,
    ("file_bank", "restoral_order_complete"): 160,
    ("file_bank", "miner_exit_prep"): 140,
    ("file_bank", "upload_declaration"): 400,
    ("file_bank", "upload_filler"): 350,
    # audit: proof blobs + quorum bookkeeping
    ("audit", "submit_proof"): 500,
    ("audit", "submit_verify_result"): 450,
    ("audit", "save_challenge_info"): 600,
    # offences
    ("offences", "heartbeat"): 60,
    ("offences", "report_offence"): 900,
    # evm (reference runtime/src/lib.rs:1322-1344 gas→weight mapping)
    ("evm", "deposit"): 80,
    ("evm", "withdraw"): 90,
    ("evm", "transact_call"): 1500,
    ("evm", "transact_create"): 2500,
}

# A block author can include a call outside the dispatch table (it fails
# with a deterministic receipt) — the overweight check must still price
# it identically on every replica, so unknown calls get a fixed default.
DEFAULT_WEIGHT = 500

# Operational (Pays::No + operational DispatchClass role): consensus
# plumbing the chain itself submits — heartbeats, offence evidence, and
# the audit OCW's challenge votes.  Free of charge and priority-boosted
# so a fee-market flood can never starve liveness machinery.
OPERATIONAL: frozenset[tuple[str, str]] = frozenset({
    ("offences", "heartbeat"),
    ("offences", "report_offence"),
    ("audit", "save_challenge_info"),
})

# Priority boost for operational extrinsics: above any achievable
# fee-per-weight (Substrate's operational class gets 3/4 of the u64
# priority space for the same reason).
OPERATIONAL_BOOST = 1 << 62


def weight_of(module: str, call: str) -> int:
    return WEIGHTS.get((module, call), DEFAULT_WEIGHT)


def is_operational(module: str, call: str) -> bool:
    return (module, call) in OPERATIONAL


def priority(fee: Balance, tip: Balance, weight: int,
             operational: bool = False) -> int:
    """Pool ordering key: fee-per-weight scaled ×1000 so sub-unit
    differences still rank (integer math only — priority feeds pool
    ordering, never consensus state)."""
    p = ((fee + tip) * 1000) // max(1, weight)
    return p + OPERATIONAL_BOOST if operational else p


class FeesPallet:
    """Fee charging + per-block split accounting (pallet-transaction-
    payment + DealWithFees collapsed into one pallet)."""

    def __init__(self, state: ChainState, base_fee: Balance,
                 fee_per_weight: Balance, block_weight_limit: int) -> None:
        self.state = state
        self.base_fee = base_fee
        self.fee_per_weight = fee_per_weight
        self.block_weight_limit = block_weight_limit
        # Escrowed fees of the block being built (zero at snapshot).
        self.block_fees: Balance = 0
        # Lifetime counters — consensus state, replica-identical.
        self.total_fees: Balance = 0
        self.paid_author: dict[str, Balance] = {}
        self.paid_treasury: Balance = 0

    # ------------------------------------------------------------ pricing

    def fee_of(self, module: str, call: str) -> Balance:
        """base + weight·per-weight (pallet-transaction-payment's
        length+weight fee with the length term folded into base)."""
        if is_operational(module, call):
            return 0
        return self.base_fee + weight_of(module, call) * self.fee_per_weight

    def can_pay(self, who: str, module: str, call: str,
                tip: Balance = 0) -> bool:
        return self.state.balances.free(who) >= self.fee_of(
            module, call) + tip

    # ------------------------------------------------------------ charging

    def charge(self, who: str, module: str, call: str,
               tip: Balance = 0) -> Balance:
        """Debit the fee (+ tip) into the block escrow pot.  Raises
        DispatchError (via ensure) when the signer can't pay — callers
        turn that into a deterministic failed receipt.  Returns the
        amount charged."""
        ensure(tip >= 0, MOD, "NegativeTip")
        fee = self.fee_of(module, call)
        total = fee + tip
        if total == 0:
            return 0
        self.state.balances.transfer(who, FEE_POT, total)
        self.block_fees += total
        self.total_fees += total
        self.state.deposit_event(
            MOD, "TransactionFeePaid", who=who, actual_fee=fee, tip=tip)
        return total

    def distribute(self, author: str) -> tuple[Balance, Balance]:
        """Split the block's escrowed fees 20/80 treasury/author at
        block commit (the DealWithFees route).  Floor division gives
        the treasury its exact 20% floor and the author the remainder,
        so the split is bit-identical on every replica.  Returns
        (treasury_amount, author_amount)."""
        total = self.block_fees
        if total == 0:
            return 0, 0
        self.block_fees = 0
        to_treasury = TREASURY_CUT.mul_floor(total)
        to_author = total - to_treasury
        self.state.balances.transfer(FEE_POT, TREASURY_POT, to_treasury)
        self.state.balances.transfer(FEE_POT, author, to_author)
        self.paid_treasury += to_treasury
        self.paid_author[author] = (
            self.paid_author.get(author, 0) + to_author)
        self.state.deposit_event(
            MOD, "FeesDistributed", author=author,
            to_author=to_author, to_treasury=to_treasury)
        return to_treasury, to_author
