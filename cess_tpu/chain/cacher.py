"""CDN cache market: cacher registry + download bills.

Re-design of the reference cacher pallet (reference:
c-pallets/cacher/src/{lib,types}.rs): cachers advertise a per-byte price;
users settle download bills with direct batch transfers.
"""

from __future__ import annotations

from dataclasses import dataclass

from .state import ChainState
from .types import AccountId, Balance, ensure

MOD = "cacher"

BILLS_LIMIT = 10


@dataclass
class CacherInfo:
    """reference: cacher/src/types.rs:9-15"""

    payee: AccountId
    ip: bytes
    byte_price: Balance


@dataclass
class Bill:
    """reference: cacher/src/types.rs:18-28"""

    id: bytes
    to: AccountId
    amount: Balance
    file_hash: str
    slice_hash: str
    expiration_time: int


class CacherPallet:
    def __init__(self, state: ChainState) -> None:
        self.state = state
        self.cachers: dict[AccountId, CacherInfo] = {}

    def register(self, sender: AccountId, info: CacherInfo) -> None:
        ensure(sender not in self.cachers, MOD, "AlreadyRegistered")
        self.cachers[sender] = info
        self.state.deposit_event(MOD, "Register", acc=sender)

    def update(self, sender: AccountId, info: CacherInfo) -> None:
        ensure(sender in self.cachers, MOD, "UnRegistered")
        self.cachers[sender] = info
        self.state.deposit_event(MOD, "Update", acc=sender)

    def logout(self, sender: AccountId) -> None:
        ensure(sender in self.cachers, MOD, "UnRegistered")
        del self.cachers[sender]
        self.state.deposit_event(MOD, "Logout", acc=sender)

    def pay(self, sender: AccountId, bills: list[Bill]) -> None:
        """Batch transfer settlement (reference: cacher/src/lib.rs:137-150)."""
        ensure(len(bills) <= BILLS_LIMIT, MOD, "LengthExceedsLimit")
        for bill in bills:
            self.state.balances.transfer(sender, bill.to, bill.amount)
        self.state.deposit_event(
            MOD, "Pay", acc=sender, count=len(bills)
        )
