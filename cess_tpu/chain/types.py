"""Core protocol types, constants and fixed-point arithmetic.

Mirrors the reference's shared primitives (reference:
primitives/common/src/lib.rs:16,53-62,76-85 and the Perbill fixed-point type
from Substrate's sp-arithmetic) with exact integer semantics: every
percentage/proportion computation in the protocol is floor arithmetic over
parts-per-billion, so results are bit-identical across Python, C++ and the
JAX verifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# ---------------------------------------------------------------- units

KIB = 1024
MIB = 1024 * KIB
G_BYTE = 1024 * MIB
T_BYTE = 1024 * G_BYTE

# File geometry (reference: primitives/common/src/lib.rs:60-62,
# runtime/src/lib.rs:1024-1025).
SEGMENT_SIZE = 16 * MIB
FRAGMENT_SIZE = 8 * MIB
CHUNK_COUNT = 1024
FRAGMENT_COUNT = 3       # 2 data + 1 parity per segment
SEGMENT_COUNT_MAX = 1000

# Token (12-decimal base unit as in the reference chain spec).
TOKEN = 10**12

# Block cadence (reference: runtime/src/lib.rs:234,245).
MILLISECS_PER_BLOCK = 6000
BLOCKS_PER_DAY = 24 * 60 * 60 * 1000 // MILLISECS_PER_BLOCK  # 14400
BLOCKS_PER_HOUR = 60 * 60 * 1000 // MILLISECS_PER_BLOCK      # 600

AccountId = str
Balance = int
BlockNumber = int


# ---------------------------------------------------------------- errors


class DispatchError(Exception):
    """An extrinsic failed; the caller must treat state as unmodified.

    Pallet methods follow checks-first discipline (validate everything, then
    mutate), matching FRAME's #[transactional] rollback semantics without a
    snapshotting store.
    """

    def __init__(self, module: str, name: str, detail: str = "") -> None:
        self.module, self.name, self.detail = module, name, detail
        super().__init__(f"{module}::{name}" + (f" ({detail})" if detail else ""))


def ensure(cond: bool, module: str, name: str, detail: str = "") -> None:
    if not cond:
        raise DispatchError(module, name, detail)


# ---------------------------------------------------------------- Perbill


BILLION = 1_000_000_000


class Perbill:
    """Parts-per-billion fixed point, floor semantics (sp-arithmetic Perbill).

    `from_rational(p, q)` rounds the ratio down to the nearest billionth and
    `mul_floor` floors the product — the exact integer pipeline the reference
    uses for power shares, reward splits and punishments
    (reference: c-pallets/sminer/src/lib.rs:654-722).
    """

    __slots__ = ("parts",)

    def __init__(self, parts: int) -> None:
        if not 0 <= parts <= BILLION:
            raise ValueError(f"Perbill parts out of range: {parts}")
        self.parts = parts

    @classmethod
    def from_percent(cls, pct: int) -> "Perbill":
        return cls(min(pct, 100) * (BILLION // 100))

    @classmethod
    def from_rational(cls, p: int, q: int) -> "Perbill":
        # sp-arithmetic clamps the denominator to >=1 (so 0/0 -> 0) and
        # saturates p/q at one.
        q = max(q, 1)
        if p >= q:
            return cls(BILLION)
        return cls(p * BILLION // q)

    def mul_floor(self, value: int) -> int:
        return value * self.parts // BILLION

    def __repr__(self) -> str:  # pragma: no cover
        return f"Perbill({self.parts})"


# ---------------------------------------------------------------- events


@dataclass(frozen=True)
class Event:
    """A deposited runtime event — the protocol's audit trail (every
    reference extrinsic deposits one, e.g. file-bank/src/lib.rs:175-208)."""

    pallet: str
    name: str
    fields: tuple = field(default_factory=tuple)

    @classmethod
    def of(cls, pallet: str, name: str, **fields) -> "Event":
        return cls(pallet, name, tuple(sorted(fields.items())))

    def get(self, key: str):
        return dict(self.fields)[key]
