"""OSS gateway registry + delegated-operator authorization.

Re-design of the reference oss pallet (reference: c-pallets/oss/src/lib.rs):
users authorize one operator account to act for them (upload/delete via
check_permission in file-bank), and gateway providers register an endpoint.
"""

from __future__ import annotations

from .state import ChainState
from .types import AccountId, ensure

MOD = "oss"


class OssPallet:
    def __init__(self, state: ChainState) -> None:
        self.state = state
        self.authority_list: dict[AccountId, AccountId] = {}  # owner -> operator
        self.oss: dict[AccountId, bytes] = {}  # account -> endpoint/peer id

    def authorize(self, sender: AccountId, operator: AccountId) -> None:
        """reference: oss/src/lib.rs:85-96 — one operator per owner
        (re-authorizing replaces)."""
        self.authority_list[sender] = operator
        self.state.deposit_event(MOD, "Authorize", acc=sender, operator=operator)

    def cancel_authorize(self, sender: AccountId) -> None:
        ensure(sender in self.authority_list, MOD, "NoAuthorization")
        del self.authority_list[sender]
        self.state.deposit_event(MOD, "CancelAuthorize", acc=sender)

    def register(self, sender: AccountId, endpoint: bytes) -> None:
        ensure(sender not in self.oss, MOD, "Registered")
        self.oss[sender] = endpoint
        self.state.deposit_event(MOD, "OssRegister", acc=sender, endpoint=endpoint)

    def update(self, sender: AccountId, endpoint: bytes) -> None:
        ensure(sender in self.oss, MOD, "UnRegister")
        self.oss[sender] = endpoint
        self.state.deposit_event(MOD, "OssUpdate", acc=sender, new_endpoint=endpoint)

    def destroy(self, sender: AccountId) -> None:
        ensure(sender in self.oss, MOD, "UnRegister")
        del self.oss[sender]
        self.state.deposit_event(MOD, "OssDestroy", acc=sender)

    # OssFindAuthor trait (reference: oss/src/lib.rs:161-172)
    def is_authorized(self, owner: AccountId, operator: AccountId) -> bool:
        return self.authority_list.get(owner) == operator
