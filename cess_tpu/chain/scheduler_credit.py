"""TEE-scheduler reputation: processed-bytes credit with decayed history.

Re-design of the reference scheduler-credit pallet (reference:
c-pallets/scheduler-credit/src/lib.rs):

 * per-period counters of bytes processed and punishments per TEE controller;
 * credit value = share_of_total×1000 − (10×punishments)², floored at 0
   (lib.rs:45-75);
 * per-period rollover on_initialize (lib.rs:112-124), keeping 5 periods of
   history;
 * credit score = weighted sum of the last 5 periods at 50/20/15/10/5%
   (lib.rs:36-42, 187-227) — fed into validator election (ValidatorCredits).
"""

from __future__ import annotations

from dataclasses import dataclass

from .state import ChainState
from .types import AccountId, Perbill

MOD = "scheduler_credit"

FULL_CREDIT_SCORE = 1000
# Percent weights for periods n-1 .. n-5 (reference: lib.rs:36-42).
PERIOD_WEIGHT = (50, 20, 15, 10, 5)


@dataclass
class SchedulerCounterEntry:
    proceed_block_size: int = 0
    punishment_count: int = 0

    def punishment_part(self) -> int:
        if self.punishment_count != 0:
            return (10 * self.punishment_count) ** 2
        return 0

    def figure_credit_value(self, total_block_size: int) -> int:
        """reference: lib.rs:62-68 (saturating subtraction)."""
        if total_block_size != 0:
            a = Perbill.from_rational(
                self.proceed_block_size, total_block_size
            ).mul_floor(FULL_CREDIT_SCORE)
            return max(0, a - self.punishment_part())
        return 0


class SchedulerCreditPallet:
    def __init__(self, state: ChainState, period_duration: int) -> None:
        self.state = state
        self.period_duration = period_duration
        self.current_counters: dict[AccountId, SchedulerCounterEntry] = {}
        # period -> controller -> credit value
        self.history_credit_values: dict[int, dict[AccountId, int]] = {}
        # controller -> stash resolution (SchedulerStashAccountFinder,
        # reference: runtime/src/impls.rs:30-40); wired by the runtime.
        self.stash_of: dict[AccountId, AccountId] = {}

    # -- SchedulerCreditCounter trait (reference: lib.rs:230-240) -------

    def record_proceed_block_size(self, scheduler: AccountId, size: int) -> None:
        self.current_counters.setdefault(
            scheduler, SchedulerCounterEntry()
        ).proceed_block_size += size

    def record_punishment(self, scheduler: AccountId) -> None:
        self.current_counters.setdefault(
            scheduler, SchedulerCounterEntry()
        ).punishment_count += 1

    # -- hooks ----------------------------------------------------------

    def on_initialize(self, now: int) -> None:
        if now % self.period_duration == 0:
            period = now // self.period_duration
            self.figure_credit_values(max(0, period - 1))

    def figure_credit_values(self, period: int) -> None:
        """Roll the live counters into history for `period` and reset
        (reference: lib.rs:144-185)."""
        total = sum(e.proceed_block_size for e in self.current_counters.values())
        snapshot = {
            acc: entry.figure_credit_value(total)
            for acc, entry in self.current_counters.items()
        }
        self.history_credit_values[period] = snapshot
        self.current_counters.clear()
        history_depth = len(PERIOD_WEIGHT)
        if period >= history_depth:
            self.history_credit_values.pop(period - history_depth, None)

    # -- scoring (reference: lib.rs:187-227, 242-251) -------------------

    def figure_credit_scores(self) -> dict[AccountId, int]:
        period = self.state.block_number // self.period_duration
        if period == 0:
            return {}
        last = period - 1
        result: dict[AccountId, int] = {}
        for ctrl in self.history_credit_values.get(last, {}):
            stash = self.stash_of.get(ctrl)
            if stash is None:
                continue
            score = 0
            for index, weight in enumerate(PERIOD_WEIGHT):
                if last >= index:
                    value = self.history_credit_values.get(last - index, {}).get(
                        ctrl, 0
                    )
                    score += Perbill.from_percent(weight).mul_floor(value)
            result[stash] = score
        return result

    # ValidatorCredits trait
    @staticmethod
    def full_credit() -> int:
        return FULL_CREDIT_SCORE

    def credits(self, _epoch_index: int = 0) -> dict[AccountId, int]:
        return self.figure_credit_scores()
