"""Checkpoint / resume: canonical state codec, state hash, snapshot.

The reference's chain database IS its checkpoint — nodes resume from the
persisted state trie, bootstrap via GRANDPA warp sync, and migrate
storage layouts on upgrade (reference: node/src/service.rs:259-263 warp
sync; c-pallets/audit/src/migrations.rs:9-41 versioned migrations;
node/src/cli.rs:48-66 ExportState/ImportBlocks).  This module provides
the equivalents for the framework's in-memory runtime:

 * `state_encode(rt)` — a CANONICAL, type-tagged byte encoding of every
   pallet's storage (sorted mappings, tuple/list distinguished, closed
   under the value types the pallets use).  Two runtimes that executed
   the same extrinsics encode identically, byte for byte.
 * `state_hash(rt)` — the sparse-Merkle root over the keyed leaves of
   that encoding (chain/smt.py, `state_leaves`): the replay-determinism
   anchor (same genesis + same extrinsics ⇒ same hash), asserted in
   tests/test_checkpoint.py.  This full rebuild is the bit-identity
   ORACLE for the incremental root the node maintains per block
   (chain/state.py StateDB — O(touched) instead of O(N)).
 * `snapshot(rt)` / `restore(rt, blob)` — ExportState/warp-sync shape.
   The blob is a VERSIONED header (magic + format version) over the
   canonical encoding: a pure data format with its own decoder — no
   pickle, so an untrusted blob can at worst fail to parse, never
   execute code.  Sync catch-up exchanges these blobs between nodes of
   possibly different builds, so `restore` upgrades older payloads
   through the MIGRATIONS registry (the storage-migration role,
   reference: c-pallets/audit/src/migrations.rs:9-41) and rejects
   blobs newer than this build.  Restoring loads the data into a
   FRESHLY CONSTRUCTED runtime (same genesis config); wiring — pallet
   cross-references, injected verifiers, backends — is re-created by
   construction and never travels.

Attribute classification is LOUD: plain data is captured; known
structural values (pallet cross-references, ChainState back-refs,
callables, the nested Balances/Agenda helpers) are skipped or recursed
by explicit rule; anything else raises, so a new pallet field of an
unsupported type fails tests instead of silently vanishing from the
hash.  (Off-chain actors' stores — the node sim's miner fragment stores
— are not chain state, exactly as miner disks are not part of the
reference's chain DB.)
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any

from . import smt

_PALLETS = (
    "state",
    "sminer",
    "storage_handler",
    "oss",
    "cacher",
    "scheduler_credit",
    "staking",
    "session",
    "offences",
    "tee_worker",
    "file_bank",
    "audit",
    "rrsc",
    "evm",
    "fees",
)

# Nested data-bearing helpers the extractor recurses into.
_NESTED_TYPES = {"Balances", "Agenda"}

# Injected-callable slots: wiring, never state — excluded even when unset
# (None), so the hash does not depend on whether a verifier is plugged in.
# `_observers` (session) and `evidence_verifier` (offences) are runtime
# wiring re-created by construction; session observer callbacks and the
# node-layer evidence closure must never travel in a blob.
_WIRING_FIELDS = {
    "result_verifier", "cert_verifier", "_observers", "evidence_verifier",
}

# Offchain-local storage: per-node worker state (the reference keeps it
# in the offchain DB, not the state trie).  Each validator's OCW lock
# advances independently, so including it would make replica state
# hashes diverge the moment different authorities run their workers.
_OFFCHAIN_FIELDS = {"_ocw_lock"}

# PATH-scoped exclusions ("pallet.attribute"): `state.events` is the
# deposited-event sink (ChainState.events).  Events are DERIVED from
# execution — deterministic and bit-identical across replicas
# (asserted via chain_getEvents in the lockstep tests) — but they are
# the chain's audit trail, not its state, exactly as the reference
# keeps events in per-block storage outside the state trie; hashing
# them would also make the consensus hash grow with history instead of
# live state.  The node service drains them into a per-block ring
# (NodeService.events_by_block) at each commit.  Scoped by PATH, not
# bare name, so a future pallet attribute that happens to be called
# `events` still lands in the hash (or trips the loud classifier)
# instead of silently vanishing.
_EXCLUDED_PATHS = {"state.events"}


def _is_structural(value: Any) -> bool:
    """Pallet cross-references and similar wiring reachable from pallet
    attributes — reconstructed by Runtime.__init__, never serialized."""
    tname = type(value).__name__
    return (
        callable(value)
        or tname.endswith("Pallet")
        or tname in ("ChainState", "Runtime", "RuntimeConfig")
    )


def _is_data(value: Any) -> bool:
    if value is None or isinstance(value, (bool, int, str, bytes, float)):
        return True
    if isinstance(value, (list, tuple, set, frozenset)):
        return all(_is_data(v) for v in value)
    if isinstance(value, dict):
        return all(_is_data(k) and _is_data(v) for k, v in value.items())
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return all(
            _is_data(getattr(value, f.name))
            for f in dataclasses.fields(value)
        )
    return False


def _object_state(
    obj: Any, where: str,
    skip: "set[tuple[str, str]] | frozenset" = frozenset(),
) -> dict[str, Any]:
    """The data attributes of a pallet-like object.  Loud on anything
    that is neither data nor a recognized structural reference.  `skip`
    holds (pallet, dotted-attr) surfaces the caller tracks elsewhere
    (StateDB's write-through maps): they are dropped BEFORE the _is_data
    walk — validating a million-entry map the caller will discard is
    what made the per-commit compare-scan O(N)."""
    out = {}
    pallet, _, parent = where.partition(".")
    for name, value in vars(obj).items():
        if (name in _WIRING_FIELDS or name in _OFFCHAIN_FIELDS
                or f"{where}.{name}" in _EXCLUDED_PATHS):
            continue
        if skip and (
            pallet, f"{parent}.{name}" if parent else name
        ) in skip:
            continue
        if _is_data(value):
            out[name] = value
        elif _is_structural(value):
            continue
        elif type(value).__name__ in _NESTED_TYPES:
            out[name] = (
                "__nested__",
                type(value).__name__,
                _object_state(value, f"{where}.{name}", skip),
            )
        else:
            raise TypeError(
                f"{where}.{name}: {type(value).__name__} is neither chain "
                "state nor recognized wiring — extend checkpoint.py "
                "explicitly so it cannot be dropped silently"
            )
    return out


def _extract(
    rt, skip: "set[tuple[str, str]] | frozenset" = frozenset()
) -> dict[str, dict[str, Any]]:
    return {
        name: _object_state(getattr(rt, name), name, skip)
        for name in _PALLETS
    }


# ------------------------------------------------------------ keyed leaves
#
# The sparse-Merkle state commitment (chain/smt.py) hashes the SAME
# extracted surfaces, cut into keyed leaves: most pallet attributes are
# one leaf each (their canonical encoding is the leaf value), but the
# maps in KEYED_MAPS — the surfaces that grow with usage and that
# stateless clients read — get ONE LEAF PER ENTRY, so touching one
# account re-hashes one path instead of re-encoding a million, and an
# account/file/deal read is provable on its own.

# (pallet, attr) map attributes committed entry-by-entry.  Membership is
# CONSENSUS-CRITICAL: moving a map in or out changes every root.
KEYED_MAPS = {
    ("state", "balances.accounts"),
    ("state", "nonces"),
    ("file_bank", "deal_map"),
    ("file_bank", "file"),
}


def canon_bytes(value: Any) -> bytes:
    """One value through the canonical codec."""
    out: list[bytes] = []
    _canon(value, out)
    return b"".join(out)


def decode_value(enc: bytes) -> Any:
    """Inverse of canon_bytes (exactly one value, no trailing bytes)."""
    reader = _Reader(enc, _dataclass_registry())
    value = reader.read()
    if reader.off != len(enc):
        raise ValueError("trailing bytes in encoded value")
    return value


def leaf_label(pallet: str, attr: str) -> bytes:
    return f"{pallet}:{attr}".encode()


def _flatten_fields(
    pallet: str,
    prefix: str,
    fields: dict[str, Any],
    out: dict[bytes, tuple[str, str, bytes | None, bytes]],
    skip: set[tuple[str, str]],
) -> None:
    for name, value in fields.items():
        attr = f"{prefix}{name}"
        if (
            isinstance(value, (tuple, list))
            and len(value) == 3
            and value[0] == "__nested__"
        ):
            _flatten_fields(pallet, f"{attr}.", value[2], out, skip)
            continue
        if (pallet, attr) in skip:
            continue
        label = leaf_label(pallet, attr)
        if (pallet, attr) in KEYED_MAPS and isinstance(value, dict):
            for k, v in value.items():
                kenc = canon_bytes(k)
                out[smt.key_path(label, kenc)] = (
                    pallet, attr, kenc, canon_bytes(v),
                )
        else:
            out[smt.key_path(label)] = (pallet, attr, None, canon_bytes(value))


def state_leaves(
    rt=None,
    extract: dict[str, dict[str, Any]] | None = None,
    skip: set[tuple[str, str]] = frozenset(),
) -> dict[bytes, tuple[str, str, bytes | None, bytes]]:
    """Keyed-leaf view of the chain state: tree path → (pallet, attr,
    map-key encoding | None, value encoding).  Accepts either a live
    runtime or an already-decoded payload dict (blob verification)."""
    if extract is None:
        extract = _extract(rt, skip=set(skip))
    out: dict[bytes, tuple[str, str, bytes | None, bytes]] = {}
    for pallet, fields in extract.items():
        _flatten_fields(pallet, "", fields, out, set(skip))
    return out


def _leaves_root_hex(
    leaves: dict[bytes, tuple[str, str, bytes | None, bytes]]
) -> str:
    tree = smt.SparseMerkleTree({p: m[3] for p, m in leaves.items()})
    return tree.root().hex()


def verify_read(
    root_hex: str, pallet: str, attr: str, proof_wire: dict, key=None
) -> tuple[bool, Any]:
    """STATELESS read verification: check a served proof against a
    (justified) state root and return (present, decoded value) — no
    runtime, no tree, no local state.  Raises smt.ProofError on any
    proof that does not commit to the root."""
    label = leaf_label(pallet, attr)
    path = smt.key_path(label, b"" if key is None else canon_bytes(key))
    present, enc = smt.verify_proof(
        bytes.fromhex(root_hex), path, smt.Proof.from_wire(proof_wire)
    )
    return present, decode_value(enc) if present else None


def verify_read_batch(
    root_hex: str,
    reads: list[tuple[str, str, Any]],
    proof_wires: list[dict],
) -> list[tuple[bool, Any]]:
    """verify_read over a `state_getProofBatch` reply: one (present,
    value) per (pallet, attr, key) read, EVERY wire checked against the
    same root — the caller's justified anchor, not whatever root the
    server claims.  Raises smt.ProofError on the first wire that does
    not commit to it, and ValueError on a length mismatch (a server
    that answered a different batch)."""
    if len(reads) != len(proof_wires):
        raise ValueError(
            f"{len(proof_wires)} proofs for {len(reads)} reads"
        )
    return [
        verify_read(root_hex, pallet, attr, wire, key=key)
        for (pallet, attr, key), wire in zip(reads, proof_wires)
    ]


def _apply(obj: Any, data: dict[str, Any]) -> None:
    for name, value in data.items():
        if (
            isinstance(value, (tuple, list))
            and len(value) == 3
            and value[0] == "__nested__"
        ):
            _apply(getattr(obj, name), value[2])
        else:
            setattr(obj, name, value)


# ---------------------------------------------------------------- codec
# Type-tagged canonical serialization: N/B/I/F/S/Y scalars, L list,
# T tuple, E set, e frozenset, D dict (sorted), C dataclass.


def _canon(value: Any, out: list[bytes]) -> None:
    if value is None:
        out.append(b"N")
    elif isinstance(value, bool):
        out.append(b"B1" if value else b"B0")
    elif isinstance(value, int):
        raw = value.to_bytes(
            (value.bit_length() + 8) // 8 or 1, "big", signed=True
        )
        out.append(b"I" + len(raw).to_bytes(4, "big") + raw)
    elif isinstance(value, float):
        raw = repr(value).encode()
        out.append(b"F" + len(raw).to_bytes(2, "big") + raw)
    elif isinstance(value, str):
        raw = value.encode()
        out.append(b"S" + len(raw).to_bytes(4, "big") + raw)
    elif isinstance(value, bytes):
        out.append(b"Y" + len(value).to_bytes(4, "big") + value)
    elif isinstance(value, (list, tuple)):
        tag = b"L" if isinstance(value, list) else b"T"
        out.append(tag + len(value).to_bytes(4, "big"))
        for v in value:
            _canon(v, out)
    elif isinstance(value, (set, frozenset)):
        tag = b"E" if isinstance(value, set) else b"e"
        parts: list[bytes] = []
        for v in value:
            sub: list[bytes] = []
            _canon(v, sub)
            parts.append(b"".join(sub))
        parts.sort()
        out.append(tag + len(parts).to_bytes(4, "big") + b"".join(parts))
    elif isinstance(value, dict):
        items: list[tuple[bytes, Any]] = []
        for k, v in value.items():
            sub: list[bytes] = []
            _canon(k, sub)
            items.append((b"".join(sub), v))
        items.sort(key=lambda kv: kv[0])
        out.append(b"D" + len(items).to_bytes(4, "big"))
        for kraw, v in items:
            out.append(kraw)
            _canon(v, out)
    elif dataclasses.is_dataclass(value) and not isinstance(value, type):
        fields = dataclasses.fields(value)
        cname = type(value).__name__.encode()
        out.append(
            b"C"
            + len(cname).to_bytes(1, "big")
            + cname
            + len(fields).to_bytes(2, "big")
        )
        for f in fields:
            _canon(f.name, out)
            _canon(getattr(value, f.name), out)
    else:  # pragma: no cover - _object_state filters these out
        raise TypeError(f"non-canonical value {type(value)!r}")


class _Reader:
    def __init__(self, data: bytes, registry: dict[str, type]) -> None:
        self.data = data
        self.off = 0
        self.registry = registry

    def take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise ValueError("truncated snapshot")
        out = self.data[self.off : self.off + n]
        self.off += n
        return out

    def read(self) -> Any:
        tag = self.take(1)
        if tag == b"N":
            return None
        if tag == b"B":
            return self.take(1) == b"1"
        if tag == b"I":
            n = int.from_bytes(self.take(4), "big")
            return int.from_bytes(self.take(n), "big", signed=True)
        if tag == b"F":
            n = int.from_bytes(self.take(2), "big")
            # cesslint: allow[det-float] decoder for the F tag: the
            # encoder wrote repr(x), and float(repr(x)) round-trips
            # bit-exactly on every IEEE-754 platform
            return float(self.take(n).decode())
        if tag == b"S":
            n = int.from_bytes(self.take(4), "big")
            return self.take(n).decode()
        if tag == b"Y":
            n = int.from_bytes(self.take(4), "big")
            return self.take(n)
        if tag in (b"L", b"T"):
            n = int.from_bytes(self.take(4), "big")
            items = [self.read() for _ in range(n)]
            return items if tag == b"L" else tuple(items)
        if tag in (b"E", b"e"):
            n = int.from_bytes(self.take(4), "big")
            items = {self.read() for _ in range(n)}
            return items if tag == b"E" else frozenset(items)
        if tag == b"D":
            n = int.from_bytes(self.take(4), "big")
            out = {}
            for _ in range(n):
                k = self.read()
                out[k] = self.read()
            return out
        if tag == b"C":
            cn = int.from_bytes(self.take(1), "big")
            cname = self.take(cn).decode()
            nfields = int.from_bytes(self.take(2), "big")
            fields = {}
            for _ in range(nfields):
                fname = self.read()
                fields[fname] = self.read()
            cls = self.registry.get(cname)
            if cls is None:
                raise ValueError(f"unknown dataclass {cname!r} in snapshot")
            return cls(**fields)
        raise ValueError(f"bad tag {tag!r} in snapshot")


def _dataclass_registry() -> dict[str, type]:
    """name → class for every dataclass defined in the chain package (the
    value types pallet storages hold)."""
    import importlib
    import pkgutil

    import cess_tpu.chain as pkg

    out: dict[str, type] = {}
    for info in pkgutil.iter_modules(pkg.__path__):
        mod = importlib.import_module(f"cess_tpu.chain.{info.name}")
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and dataclasses.is_dataclass(obj):
                out[obj.__name__] = obj
    return out


# ------------------------------------------------------------ versioning
#
# Snapshot blobs travel between nodes (sync_checkpoint catch-up) and
# across builds (export-state files), so the format is version-tagged:
#
#   MAGIC ‖ u16 version ‖ canonical payload
#
# v1: bare canonical encoding, no header (the original format — still
#     accepted on read).
# v2: header introduced; payload layout unchanged.
# v3: VRF consensus state on the rrsc pallet (epoch-randomness
#     accumulator + fold count, cess_tpu/consensus) — epoch randomness
#     became accumulated consensus state instead of a derived snapshot.
# v4: session + offences pallets entered the replicated state
#     (chain/{session,offences}.py — session clock, historical
#     authority sets, heartbeat record, offence registry/strikes, and
#     staking's chill register).
# v5: the deposited-event sink left the consensus state (events are
#     the audit trail, kept per block outside the state hash —
#     see _OFFCHAIN_FIELDS); blobs no longer carry state.events.
# v6: the fees pallet entered the replicated state (chain/fees.py —
#     per-block fee escrow, lifetime fee totals, per-author payout
#     ledger for the 20/80 treasury/author split).
# v7: the state hash became the sparse-Merkle ROOT over keyed leaves
#     (chain/smt.py + state_leaves) instead of sha256 of the flat
#     encoding.  The blob payload layout is UNCHANGED (the migration is
#     the identity) but every state_hash a block commits to is
#     re-rooted, so v7 is consensus-incompatible with v6 heads
#     (SYNC_PROTO_VERSION bumped alongside).
#
# MIGRATIONS[v] upgrades a decoded v payload dict to v+1; restore runs
# the chain v → FORMAT_VERSION, so any supported older blob loads into
# the current runtime (the on_runtime_upgrade role, reference:
# c-pallets/audit/src/migrations.rs:9-41).  Later format bumps add an
# entry here instead of breaking old fixtures.

MAGIC = b"CESSCKPT"
FORMAT_VERSION = 7


def _migrate_v1_to_v2(data: dict) -> dict:
    """v2 introduced the versioned header; the payload itself is
    unchanged, so the migration is the identity on the decoded dict."""
    return data


def _migrate_v2_to_v3(data: dict) -> dict:
    """Pre-VRF blobs carry no accumulator: seed it empty with a zero
    fold count, which rrsc.rotate_epoch reads as "no VRF-bearing blocks
    yet" and keeps the old hash-chain rotation until outputs arrive."""
    rrsc = data.get("rrsc")
    if isinstance(rrsc, dict):
        rrsc.setdefault("vrf_accumulator", bytes(32))
        rrsc.setdefault("vrf_fold_count", 0)
    return data


def _migrate_v3_to_v4(data: dict) -> dict:
    """Pre-offences blobs carry no session/offences pallets: seed both
    EXPLICITLY empty (not merely absent) so a migrated blob restores to
    the same state on every replica regardless of what the receiving
    runtime held before — a fresh session clock, no heartbeats, no
    offences, no chills.  (session_length/sessions_per_era stay as the
    receiving runtime's genesis config derived them — consensus
    parameters, not snapshot state.)"""
    if "session" not in data:
        data["session"] = {
            "session_index": 0, "keys": {}, "historical": {},
            "historical_validators": {},
        }
    if "offences" not in data:
        data["offences"] = {
            "reports": {}, "pending": [], "heartbeats": {}, "strikes": {},
        }
    staking = data.get("staking")
    if isinstance(staking, dict):
        staking.setdefault("chilled_until", {})
    return data


def _migrate_v4_to_v5(data: dict) -> dict:
    """v4 blobs carried the cumulative event sink inside the state
    payload; v5 moved events outside the consensus state (they are
    per-block telemetry, not state), so the restored runtime starts
    with an empty sink — the per-block event ring is node bookkeeping
    rebuilt as blocks execute."""
    state = data.get("state")
    if isinstance(state, dict):
        state.pop("events", None)
    return data


def _migrate_v5_to_v6(data: dict) -> dict:
    """Pre-fee-market blobs carry no fees pallet: seed it EXPLICITLY
    zeroed (mirroring _migrate_v3_to_v4's explicit-empty rule) so a
    migrated blob restores to the same state on every replica.  The
    fee constants (base_fee / fee_per_weight / block_weight_limit) are
    genesis config, not snapshot state — the receiving runtime's values
    stand, exactly like session_length."""
    if "fees" not in data:
        data["fees"] = {
            "block_fees": 0, "total_fees": 0,
            "paid_author": {}, "paid_treasury": 0,
        }
    return data


def _migrate_v6_to_v7(data: dict) -> dict:
    """v7 re-rooted the state hash (sparse-Merkle root over keyed
    leaves) without touching the payload layout: the migration is the
    identity on the decoded dict, and the receiving node derives the
    new root from the restored state."""
    return data


MIGRATIONS = {1: _migrate_v1_to_v2, 2: _migrate_v2_to_v3,
              3: _migrate_v3_to_v4, 4: _migrate_v4_to_v5,
              5: _migrate_v5_to_v6, 6: _migrate_v6_to_v7}


# ---------------------------------------------------------------- API


def state_encode(rt) -> bytes:
    out: list[bytes] = []
    _canon(_extract(rt), out)
    return b"".join(out)


def state_hash(rt) -> str:
    """Deterministic hex digest of the full chain state: the sparse-
    Merkle root over the keyed leaves (header-independent, and the
    FULL-REBUILD bit-identity oracle for the incremental StateDB root
    in chain/state.py)."""
    return _leaves_root_hex(state_leaves(rt))


def encode_events(events: list) -> bytes:
    """Canonical byte encoding of a deposited-event list (the same
    type-tagged codec the state hash uses).  Replicas that executed
    one block identically encode its events byte-for-byte identically
    — the bit-identity contract `chain_getEvents` is asserted on."""
    out: list[bytes] = []
    _canon(list(events), out)
    return b"".join(out)


def events_digest(events: list) -> str:
    """blake2b-256 over encode_events — the per-block event commitment
    served next to the event list so replicas can be diffed cheaply."""
    return hashlib.blake2b(
        encode_events(events), digest_size=32
    ).hexdigest()


def snapshot(rt) -> bytes:
    """Serialized chain state (the ExportState role): versioned header
    over the canonical encoding."""
    return snapshot_and_hash(rt)[0]


def snapshot_and_hash(rt) -> tuple[bytes, str]:
    """One extraction pass for callers that need both the blob and the
    state hash (genesis, checkpoint cadence, export-state): the hash is
    the sparse-Merkle root over the same extracted surfaces the blob
    encodes."""
    extract = _extract(rt)
    out: list[bytes] = []
    _canon(extract, out)
    payload = b"".join(out)
    header = MAGIC + FORMAT_VERSION.to_bytes(2, "big")
    return header + payload, _leaves_root_hex(state_leaves(extract=extract))


def blob_payload_hash(blob: bytes) -> str:
    """State hash a CURRENT-version blob's payload commits to — the
    integrity gate the on-disk store (node/store.py) runs before
    restoring a checkpoint: the value must equal the state_hash the
    signed head block commits to, so a torn or bit-flipped checkpoint
    file fails closed before any restore work.  Since v7 this decodes
    the payload and roots its keyed leaves (checkpoint-cadence cost,
    never per block).  Only meaningful for FORMAT_VERSION blobs (older
    versions hash differently after migration); anything else raises."""
    if not blob.startswith(MAGIC):
        raise ValueError("headerless blob has no comparable payload hash")
    version = int.from_bytes(blob[len(MAGIC):len(MAGIC) + 2], "big")
    if version != FORMAT_VERSION:
        raise ValueError(
            f"payload hash is version-bound (blob v{version}, "
            f"build v{FORMAT_VERSION})"
        )
    payload = blob[len(MAGIC) + 2:]
    reader = _Reader(payload, _dataclass_registry())
    data = reader.read()
    if reader.off != len(payload):
        raise ValueError("trailing bytes in snapshot")
    if not isinstance(data, dict):
        raise ValueError("snapshot payload is not a state mapping")
    return _leaves_root_hex(state_leaves(extract=data))


def decode_blob(blob: bytes) -> tuple[int, dict]:
    """Parse a snapshot blob → (version, payload dict), migrations NOT
    yet applied.  Headerless blobs are v1 (the pre-header format)."""
    version = 1
    if blob.startswith(MAGIC):
        version = int.from_bytes(blob[len(MAGIC):len(MAGIC) + 2], "big")
        blob = blob[len(MAGIC) + 2:]
    reader = _Reader(blob, _dataclass_registry())
    data = reader.read()
    if reader.off != len(blob):
        raise ValueError("trailing bytes in snapshot")
    if not isinstance(data, dict):
        raise ValueError("snapshot payload is not a state mapping")
    return version, data


def restore(rt, blob: bytes) -> None:
    """Load a snapshot into a freshly constructed runtime (same genesis
    config), upgrading older format versions through MIGRATIONS.
    Wiring (pallet cross-refs, verifiers, backend) stays as the fresh
    construction made it; only data state is replaced.  The blob is
    parsed by the canonical decoder — malformed input raises ValueError,
    nothing in the format can execute code."""
    version, data = decode_blob(blob)
    if version > FORMAT_VERSION:
        raise ValueError(
            f"snapshot format v{version} is newer than this build "
            f"(v{FORMAT_VERSION})"
        )
    while version < FORMAT_VERSION:
        migrate = MIGRATIONS.get(version)
        if migrate is None:
            raise ValueError(f"no migration from snapshot format v{version}")
        data = migrate(data)
        version += 1
    for name, fields in data.items():
        _apply(getattr(rt, name), fields)
