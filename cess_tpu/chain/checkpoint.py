"""Checkpoint / resume: canonical state encoding, state hash, snapshot.

The reference's chain database IS its checkpoint — nodes resume from the
persisted state trie, bootstrap via GRANDPA warp sync, and migrate
storage layouts on upgrade (reference: node/src/service.rs:259-263 warp
sync; c-pallets/audit/src/migrations.rs:9-41 versioned migrations;
node/src/cli.rs:48-66 ExportState/ImportBlocks).  This module provides
the equivalents for the framework's in-memory runtime:

 * `state_encode(rt)` — a CANONICAL byte encoding of every pallet's
   storage (sorted keys, type-tagged, closed under the value types the
   pallets use).  Two runtimes that executed the same extrinsics encode
   identically, byte for byte.
 * `state_hash(rt)` — sha256 of the encoding: the replay-determinism
   anchor (same genesis + same extrinsics ⇒ same hash), asserted in
   tests/test_checkpoint.py.
 * `snapshot(rt)` / `restore(rt, blob)` — ExportState/warp-sync shape:
   extract the pure data state, then load it into a FRESHLY CONSTRUCTED
   runtime (same genesis config).  Cross-pallet references, injected
   verifiers, and backends are re-created by construction, not
   serialized — only chain state travels.

What counts as state: plain data attributes (ints, strings, bytes,
bools, lists/tuples/sets/dicts/dataclasses of the same) reachable from
the runtime's pallets, the balance ledger, the scheduler agenda, events,
block number, and randomness.  Callables, pallet cross-references, the
ProofBackend, and config objects are structural, not state — the
extractor skips them and `restore` leaves the fresh runtime's own wiring
in place.  (Off-chain actors' stores — the node sim's miner fragment
stores — are not chain state, exactly as miner disks are not part of the
reference's chain DB.)
"""

from __future__ import annotations

import dataclasses
import hashlib
import pickle
from typing import Any

_PALLETS = (
    "state",
    "sminer",
    "storage_handler",
    "oss",
    "cacher",
    "scheduler_credit",
    "staking",
    "tee_worker",
    "file_bank",
    "audit",
)


def _is_data(value: Any) -> bool:
    if value is None or isinstance(value, (bool, int, str, bytes, float)):
        return True
    if isinstance(value, (list, tuple, set, frozenset)):
        return all(_is_data(v) for v in value)
    if isinstance(value, dict):
        return all(_is_data(k) and _is_data(v) for k, v in value.items())
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return all(
            _is_data(getattr(value, f.name))
            for f in dataclasses.fields(value)
        )
    return False


# Injected-callable slots: wiring, never state — excluded even when unset
# (None), so the hash does not depend on whether a verifier is plugged in.
_WIRING_FIELDS = {"result_verifier", "cert_verifier"}


def _object_state(obj: Any) -> dict[str, Any]:
    """The data attributes of a pallet-like object (excludes wiring)."""
    out = {}
    for name, value in vars(obj).items():
        if name in _WIRING_FIELDS:
            continue
        if _is_data(value):
            out[name] = value
        elif name.startswith("_"):
            # private wiring (e.g. Balances._state back-reference) — the
            # data-bearing privates (Agenda._by_block/_names) are plain
            # data and took the branch above.
            continue
        elif type(value).__module__.startswith("cess_tpu.chain") and hasattr(
            value, "__dict__"
        ) and not callable(value):
            # nested helper objects holding data (Balances, Agenda)
            nested = _object_state(value)
            if nested:
                out[name] = ("__nested__", type(value).__name__, nested)
    return out


def _extract(rt) -> dict[str, dict[str, Any]]:
    return {name: _object_state(getattr(rt, name)) for name in _PALLETS}


def _apply(obj: Any, data: dict[str, Any]) -> None:
    for name, value in data.items():
        if (
            isinstance(value, tuple)
            and len(value) == 3
            and value[0] == "__nested__"
        ):
            _apply(getattr(obj, name), value[2])
        else:
            setattr(obj, name, value)


# ---------------------------------------------------------------- encode


def _canon(value: Any, out: list[bytes]) -> None:
    """Type-tagged canonical serialization (sorted mappings/sets)."""
    if value is None:
        out.append(b"N")
    elif isinstance(value, bool):
        out.append(b"B1" if value else b"B0")
    elif isinstance(value, int):
        raw = value.to_bytes(
            (value.bit_length() + 8) // 8 or 1, "big", signed=True
        )
        out.append(b"I" + len(raw).to_bytes(4, "big") + raw)
    elif isinstance(value, float):
        out.append(b"F" + repr(value).encode())
    elif isinstance(value, str):
        raw = value.encode()
        out.append(b"S" + len(raw).to_bytes(4, "big") + raw)
    elif isinstance(value, bytes):
        out.append(b"Y" + len(value).to_bytes(4, "big") + value)
    elif isinstance(value, (list, tuple)):
        out.append(b"L" + len(value).to_bytes(4, "big"))
        for v in value:
            _canon(v, out)
    elif isinstance(value, (set, frozenset)):
        parts: list[bytes] = []
        for v in value:
            sub: list[bytes] = []
            _canon(v, sub)
            parts.append(b"".join(sub))
        parts.sort()
        out.append(b"E" + len(parts).to_bytes(4, "big") + b"".join(parts))
    elif isinstance(value, dict):
        items: list[tuple[bytes, Any]] = []
        for k, v in value.items():
            sub: list[bytes] = []
            _canon(k, sub)
            items.append((b"".join(sub), v))
        items.sort(key=lambda kv: kv[0])
        out.append(b"D" + len(items).to_bytes(4, "big"))
        for kraw, v in items:
            out.append(kraw)
            _canon(v, out)
    elif dataclasses.is_dataclass(value) and not isinstance(value, type):
        fields = dataclasses.fields(value)
        out.append(
            b"C"
            + type(value).__name__.encode()
            + b"/"
            + len(fields).to_bytes(2, "big")
        )
        for f in fields:
            _canon(f.name, out)
            _canon(getattr(value, f.name), out)
    else:  # pragma: no cover - _is_data filters these out
        raise TypeError(f"non-canonical value {type(value)!r}")


def state_encode(rt) -> bytes:
    out: list[bytes] = []
    _canon(_extract(rt), out)
    return b"".join(out)


def state_hash(rt) -> str:
    """Deterministic hex digest of the full chain state."""
    return hashlib.sha256(state_encode(rt)).hexdigest()


# ---------------------------------------------------------------- snapshot


def snapshot(rt) -> bytes:
    """Serialized chain state (the ExportState role)."""
    return pickle.dumps(_extract(rt), protocol=4)


def restore(rt, blob: bytes) -> None:
    """Load a snapshot into a freshly constructed runtime (same genesis
    config).  Wiring (pallet cross-refs, verifiers, backend) stays as the
    fresh construction made it; only data state is replaced."""
    data = pickle.loads(blob)
    for name, fields in data.items():
        _apply(getattr(rt, name), fields)
