"""EVM execution pallet: contract accounts, gas, and a full-featured
interpreter over the frontier-era opcode set.

Capability match: the reference gets EVM compatibility from the forked
Frontier — `pallet_evm` + `pallet_ethereum` wired at
runtime/src/lib.rs:1322-1344 with the standard precompile set
(runtime/src/precompiles.rs:23-53) and eth RPC served by the node
(node/src/rpc.rs:179-323).  This pallet is a native re-implementation of
the execution capability against the framework's deterministic
ChainState:

 * **Account model.**  20-byte H160 addresses; EVM balances live in the
   pallet ledger, bridged to the chain's native balances through the
   `evm-pot` account (`deposit`/`withdraw` — the role of Frontier's
   AddressMapping + withdraw adapter).  A native account's mapped
   address is keccak256("cess-evm:" ‖ name)[12:].

 * **Execution.**  A 256-bit stack machine implementing the arithmetic,
   comparison, keccak, environment, block-context, memory, storage,
   control-flow, logging, and system opcode families (CREATE/CREATE2/
   CALL/DELEGATECALL/STATICCALL/RETURN/REVERT/SELFDESTRUCT), with
   EIP-150-style 63/64 gas forwarding, call-depth limit 1024, value
   transfers, and full state journaling (storage, balances, nonces,
   code, logs roll back on revert/failure).

 * **Precompiles** at the standard addresses: 0x01 ecrecover,
   0x02 sha256, 0x04 identity, 0x05 modexp.

 * **Gas.**  A simplified-but-shaped schedule (constant-tier opcode
   costs, quadratic memory expansion, keccak/copy per-word costs,
   cold-SSTORE surcharge, 21000 intrinsic tx cost).  Fees =
   gas_used × gas_price are charged from the caller's EVM balance and
   credited to the block author's pot via on_fee.

What is deliberately out of scope (recorded, not omitted silently):
secp256k1 tx signatures (extrinsics arrive through the framework's
BLS-signed envelope; ecrecover remains available to contracts), the
ancient difficulty/DIFFICULTY semantics (PREVRANDAO serves the chain's
shared randomness), and fee-market EIP-1559 dynamics (flat gas_price).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..utils.keccak import keccak256
from .state import ChainState
from .types import AccountId, Balance, DispatchError, ensure

MOD = "evm"

EVM_POT = "evm-pot"  # native-side escrow for the EVM ledger
CHAIN_ID = 11330  # the CESS testnet EVM chain id
CALL_DEPTH_LIMIT = 1024
MAX_CODE_SIZE = 24576  # EIP-170

U256 = (1 << 256) - 1
_SIGN_BIT = 1 << 255


def _to_signed(x: int) -> int:
    return x - (1 << 256) if x & _SIGN_BIT else x


def _addr(x: int) -> bytes:
    return (x & ((1 << 160) - 1)).to_bytes(20, "big")


def _rlp(item) -> bytes:
    """Minimal RLP encode (bytes or nested lists) — CREATE addressing."""
    if isinstance(item, bytes):
        if len(item) == 1 and item[0] < 0x80:
            return item
        if len(item) <= 55:
            return bytes([0x80 + len(item)]) + item
        ln = len(item).to_bytes((len(item).bit_length() + 7) // 8, "big")
        return bytes([0xB7 + len(ln)]) + ln + item
    payload = b"".join(_rlp(x) for x in item)
    if len(payload) <= 55:
        return bytes([0xC0 + len(payload)]) + payload
    ln = len(payload).to_bytes((len(payload).bit_length() + 7) // 8, "big")
    return bytes([0xF7 + len(ln)]) + ln + payload


def _int_bytes(x: int) -> bytes:
    return b"" if x == 0 else x.to_bytes((x.bit_length() + 7) // 8, "big")


def create_address(sender: bytes, nonce: int) -> bytes:
    return keccak256(_rlp([sender, _int_bytes(nonce)]))[12:]


def create2_address(sender: bytes, salt: bytes, init_code: bytes) -> bytes:
    return keccak256(b"\xff" + sender + salt + keccak256(init_code))[12:]


# ------------------------------------------------------------ secp256k1

_SECP_P = 2**256 - 2**32 - 977
_SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_SECP_G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)


def _secp_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    if p[0] == q[0] and (p[1] + q[1]) % _SECP_P == 0:
        return None
    if p == q:
        lam = 3 * p[0] * p[0] * pow(2 * p[1], -1, _SECP_P) % _SECP_P
    else:
        lam = (q[1] - p[1]) * pow(q[0] - p[0], -1, _SECP_P) % _SECP_P
    x = (lam * lam - p[0] - q[0]) % _SECP_P
    return (x, (lam * (p[0] - x) - p[1]) % _SECP_P)


def _secp_mul(k: int, p):
    acc = None
    while k:
        if k & 1:
            acc = _secp_add(acc, p)
        p = _secp_add(p, p)
        k >>= 1
    return acc


def ecrecover(msg_hash: bytes, v: int, r: int, s: int) -> bytes | None:
    """Recover the signer's address (the 0x01 precompile)."""
    if not (1 <= r < _SECP_N and 1 <= s < _SECP_N and v in (27, 28)):
        return None
    x = r
    y_sq = (pow(x, 3, _SECP_P) + 7) % _SECP_P
    y = pow(y_sq, (_SECP_P + 1) // 4, _SECP_P)
    if y * y % _SECP_P != y_sq:
        return None
    if (y & 1) != (v - 27):
        y = _SECP_P - y
    z = int.from_bytes(msg_hash, "big")
    r_inv = pow(r, -1, _SECP_N)
    # Q = r^-1 (s·R − z·G)
    sR = _secp_mul(s, (x, y))
    zG = _secp_mul(z % _SECP_N, _SECP_G)
    neg_zG = None if zG is None else (zG[0], (-zG[1]) % _SECP_P)
    q = _secp_mul(r_inv, _secp_add(sR, neg_zG))
    if q is None:
        return None
    pub = q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")
    return keccak256(pub)[12:]


# ------------------------------------------------------------ data model


@dataclass
class EvmAccount:
    nonce: int = 0
    code: bytes = b""


@dataclass
class Log:
    address: bytes
    topics: list[bytes]
    data: bytes


@dataclass
class ExecResult:
    success: bool
    return_data: bytes
    gas_used: int
    logs: list[Log] = field(default_factory=list)
    contract: bytes | None = None  # CREATE target
    error: str = ""


class _Revert(Exception):
    def __init__(self, data: bytes = b""):
        self.data = data


class _Fail(Exception):
    """Exceptional halt: consumes all frame gas (out-of-gas, bad jump,
    stack violation, static-state violation…)."""

    def __init__(self, reason: str):
        self.reason = reason


# simplified gas schedule (constant tiers)
G_VERYLOW, G_LOW, G_MID, G_BASE, G_HIGH = 3, 5, 8, 2, 10
G_KECCAK, G_KECCAK_WORD = 30, 6
G_SLOAD, G_SSTORE_SET, G_SSTORE_RESET = 100, 20000, 5000
G_LOG, G_LOG_TOPIC, G_LOG_DATA = 375, 375, 8
G_CREATE, G_CALL, G_CALL_VALUE, G_NEW_ACCOUNT = 32000, 100, 9000, 25000
G_COPY_WORD, G_EXP, G_EXP_BYTE = 3, 10, 50
G_TX = 21000
G_CODE_DEPOSIT = 200  # per byte of deployed runtime code


class EvmPallet:
    def __init__(self, state: ChainState, block_time_ms: int = 6000) -> None:
        self.state = state
        self.block_time_ms = block_time_ms
        self.accounts: dict[bytes, EvmAccount] = {}
        self.storage: dict[tuple[bytes, int], int] = {}
        self.balances: dict[bytes, int] = {}
        # fees accrue here; the runtime's fee split can drain it
        self.fee_pot: int = 0

    # ------------------------------------------------------ address map

    @staticmethod
    def address_of(account: AccountId) -> bytes:
        """Native account → H160 (the AddressMapping role)."""
        return keccak256(b"cess-evm:" + account.encode())[12:]

    # ------------------------------------------------------ bridge

    def deposit(self, sender: AccountId, amount: Balance) -> bytes:
        """Move native balance into the sender's mapped EVM address."""
        ensure(amount > 0, MOD, "ZeroAmount")
        self.state.balances.transfer(sender, EVM_POT, amount)
        addr = self.address_of(sender)
        self.balances[addr] = self.balances.get(addr, 0) + amount
        self.state.deposit_event(
            MOD, "Deposit", who=sender, address=addr.hex(), amount=amount
        )
        return addr

    def withdraw(self, sender: AccountId, amount: Balance) -> None:
        addr = self.address_of(sender)
        ensure(
            self.balances.get(addr, 0) >= amount, MOD, "BalanceLow"
        )
        self.balances[addr] -= amount
        self.state.balances.transfer(EVM_POT, sender, amount)
        self.state.deposit_event(
            MOD, "Withdraw", who=sender, address=addr.hex(), amount=amount
        )

    # ------------------------------------------------------ tx entry

    def transact_call(
        self,
        sender: AccountId,
        to: bytes,
        data: bytes = b"",
        value: int = 0,
        gas_limit: int = 1_000_000,
        gas_price: int = 1,
    ) -> ExecResult:
        """Signed-extrinsic entry (pallet_evm::call role): charge the
        intrinsic cost + fee from the mapped address, execute, refund."""
        return self._transact(
            sender, to, data, value, gas_limit, gas_price, create=False
        )

    def transact_create(
        self,
        sender: AccountId,
        init_code: bytes,
        value: int = 0,
        gas_limit: int = 1_000_000,
        gas_price: int = 1,
    ) -> ExecResult:
        return self._transact(
            sender, init_code, b"", value, gas_limit, gas_price, create=True
        )

    def _transact(
        self, sender, target, data, value, gas_limit, gas_price, create
    ) -> ExecResult:
        caller = self.address_of(sender)
        ensure(gas_limit >= G_TX, MOD, "GasLimitTooLow")
        fee_max = gas_limit * gas_price
        ensure(
            self.balances.get(caller, 0) >= fee_max + value,
            MOD, "BalanceLow",
        )
        self.balances[caller] -= fee_max
        acct = self.accounts.setdefault(caller, EvmAccount())
        nonce = acct.nonce
        acct.nonce += 1
        gas = gas_limit - G_TX
        if create:
            res = self.create(
                caller, target, value=value, gas=gas, nonce=nonce
            )
        else:
            res = self.call(caller, target, data=data, value=value, gas=gas)
        gas_used = res.gas_used + G_TX
        refund = (gas_limit - gas_used) * gas_price
        self.balances[caller] = self.balances.get(caller, 0) + refund
        self.fee_pot += gas_used * gas_price
        res = ExecResult(
            res.success, res.return_data, gas_used, res.logs,
            res.contract, res.error,
        )
        self.state.deposit_event(
            MOD,
            "Executed" if res.success else "ExecutedFailed",
            who=sender,
            to=(res.contract or (target if not create else b"")).hex()
            if isinstance(res.contract or target, bytes) else "",
            gas_used=gas_used,
        )
        return res

    # ------------------------------------------------------ raw entry

    def call(
        self,
        caller: bytes,
        to: bytes,
        data: bytes = b"",
        value: int = 0,
        gas: int = 1_000_000,
    ) -> ExecResult:
        """Message call from `caller` (already an H160)."""
        snap = self._snapshot()
        logs: list[Log] = []
        try:
            ret, gas_left = self._call_frame(
                caller, to, data, value, gas, logs, static=False, depth=0
            )
            return ExecResult(True, ret, gas - gas_left, logs)
        except _Revert as rv:
            self._restore(snap)
            return ExecResult(False, rv.data, gas, error="revert")
        except _Fail as f:
            self._restore(snap)
            return ExecResult(False, b"", gas, error=f.reason)

    def create(
        self,
        caller: bytes,
        init_code: bytes,
        value: int = 0,
        gas: int = 1_000_000,
        nonce: int | None = None,
        salt: bytes | None = None,
    ) -> ExecResult:
        snap = self._snapshot()
        logs: list[Log] = []
        try:
            if nonce is None:
                acct = self.accounts.setdefault(caller, EvmAccount())
                nonce = acct.nonce
                acct.nonce += 1  # CREATE addressing consumes the nonce
            addr, gas_left = self._create_frame(
                caller, init_code, value, gas, logs, depth=0, salt=salt,
                nonce=nonce,
            )
            return ExecResult(True, b"", gas - gas_left, logs, contract=addr)
        except _Revert as rv:
            self._restore(snap)
            return ExecResult(False, rv.data, gas, error="revert")
        except _Fail as f:
            self._restore(snap)
            return ExecResult(False, b"", gas, error=f.reason)

    # ------------------------------------------------------ journaling

    def _snapshot(self):
        return (
            dict(self.storage),
            dict(self.balances),
            {a: EvmAccount(ac.nonce, ac.code) for a, ac in self.accounts.items()},
        )

    def _restore(self, snap) -> None:
        self.storage, self.balances, self.accounts = (
            dict(snap[0]), dict(snap[1]),
            {a: EvmAccount(ac.nonce, ac.code) for a, ac in snap[2].items()},
        )

    # ------------------------------------------------------ frames

    def _transfer(self, frm: bytes, to: bytes, value: int) -> None:
        if value == 0:
            return
        if self.balances.get(frm, 0) < value:
            raise _Fail("insufficient balance")
        self.balances[frm] -= value
        self.balances[to] = self.balances.get(to, 0) + value

    def _create_frame(
        self, caller, init_code, value, gas, logs, depth,
        salt=None, nonce=0,
    ):
        if depth > CALL_DEPTH_LIMIT:
            raise _Fail("call depth")
        if salt is not None:
            addr = create2_address(caller, salt, init_code)
        else:
            addr = create_address(caller, nonce)
        if self.accounts.get(addr, EvmAccount()).code:
            raise _Fail("address collision")
        self._transfer(caller, addr, value)
        acct = self.accounts.setdefault(addr, EvmAccount())
        acct.nonce = 1
        ret, gas_left = self._execute(
            caller=caller, address=addr, code=init_code, data=b"",
            value=value, gas=gas, logs=logs, static=False, depth=depth,
        )
        if len(ret) > MAX_CODE_SIZE:
            raise _Fail("code too large")
        deposit = G_CODE_DEPOSIT * len(ret)
        if gas_left < deposit:
            raise _Fail("out of gas: code deposit")
        acct.code = bytes(ret)
        return addr, gas_left - deposit

    def _call_frame(
        self, caller, to, data, value, gas, logs, static, depth,
        code_addr=None, ctx_addr=None,
    ):
        """Run a message call; returns (return_data, gas_left).  Raises
        _Revert/_Fail (caller handles sub-call containment)."""
        if depth > CALL_DEPTH_LIMIT:
            raise _Fail("call depth")
        if static and value:
            raise _Fail("static value transfer")
        ctx = ctx_addr if ctx_addr is not None else to
        if ctx_addr is None:  # regular CALL moves value
            self._transfer(caller, to, value)
        pre = self._precompile(code_addr or to, data)
        if pre is not None:
            cost, out = pre
            if cost > gas:
                raise _Fail("out of gas: precompile")
            return out, gas - cost
        code = self.accounts.get(code_addr or to, EvmAccount()).code
        if not code:
            return b"", gas
        return self._execute(
            caller=caller, address=ctx, code=code, data=data, value=value,
            gas=gas, logs=logs, static=static, depth=depth,
        )

    # ------------------------------------------------------ precompiles

    def _precompile(self, addr: bytes, data: bytes):
        which = int.from_bytes(addr, "big")
        if not 1 <= which <= 9:
            return None
        if which == 1:  # ecrecover
            buf = data.ljust(128, b"\x00")[:128]
            h, v = buf[0:32], int.from_bytes(buf[32:64], "big")
            r = int.from_bytes(buf[64:96], "big")
            s = int.from_bytes(buf[96:128], "big")
            rec = ecrecover(h, v, r, s)
            out = b"" if rec is None else rec.rjust(32, b"\x00")
            return 3000, out
        if which == 2:  # sha256
            words = -(-len(data) // 32)
            return 60 + 12 * words, hashlib.sha256(data).digest()
        if which == 4:  # identity
            words = -(-len(data) // 32)
            return 15 + 3 * words, data
        if which == 5:  # modexp (EIP-198 shape, simplified gas)
            buf = data.ljust(96, b"\x00")
            bl = int.from_bytes(buf[0:32], "big")
            el = int.from_bytes(buf[32:64], "big")
            ml = int.from_bytes(buf[64:96], "big")
            if max(bl, el, ml) > 4096:
                return None  # unpriceable: treat as empty account
            rest = data[96:].ljust(bl + el + ml, b"\x00")
            b = int.from_bytes(rest[:bl], "big")
            e = int.from_bytes(rest[bl : bl + el], "big")
            m = int.from_bytes(rest[bl + el : bl + el + ml], "big")
            out = (pow(b, e, m) if m else 0).to_bytes(ml, "big")
            cost = 200 + max(bl, ml) * max(el.bit_length(), 1) // 8
            return cost, out
        return None  # unimplemented slots behave as empty accounts

    # ------------------------------------------------------ interpreter

    def _execute(
        self, *, caller, address, code, data, value, gas, logs, static,
        depth,
    ):
        stack: list[int] = []
        mem = bytearray()
        pc = 0
        gas_left = gas
        ret_data = b""  # RETURNDATA buffer
        jumpdests = _jumpdests(code)

        def use(n: int) -> None:
            nonlocal gas_left
            gas_left -= n
            if gas_left < 0:
                raise _Fail("out of gas")

        def mem_expand(offset: int, size: int) -> None:
            if size == 0:
                return
            need = offset + size
            if need > len(mem):
                old_w = len(mem) // 32
                new_w = -(-need // 32)
                use(
                    3 * (new_w - old_w)
                    + (new_w * new_w - old_w * old_w) // 512
                )
                mem.extend(b"\x00" * (new_w * 32 - len(mem)))

        def push(x: int) -> None:
            if len(stack) >= 1024:
                raise _Fail("stack overflow")
            stack.append(x & U256)

        def pop() -> int:
            if not stack:
                raise _Fail("stack underflow")
            return stack.pop()

        def mload(off: int, size: int) -> bytes:
            mem_expand(off, size)
            return bytes(mem[off : off + size])

        while pc < len(code):
            op = code[pc]
            pc += 1

            # PUSH0..PUSH32
            if 0x5F <= op <= 0x7F:
                n = op - 0x5F
                use(G_BASE if n == 0 else G_VERYLOW)
                push(int.from_bytes(code[pc : pc + n], "big"))
                pc += n
                continue
            # DUP1..DUP16
            if 0x80 <= op <= 0x8F:
                use(G_VERYLOW)
                i = op - 0x7F
                if len(stack) < i:
                    raise _Fail("stack underflow")
                push(stack[-i])
                continue
            # SWAP1..SWAP16
            if 0x90 <= op <= 0x9F:
                use(G_VERYLOW)
                i = op - 0x8F
                if len(stack) < i + 1:
                    raise _Fail("stack underflow")
                stack[-1], stack[-1 - i] = stack[-1 - i], stack[-1]
                continue
            # LOG0..LOG4
            if 0xA0 <= op <= 0xA4:
                if static:
                    raise _Fail("static log")
                n_topics = op - 0xA0
                off, size = pop(), pop()
                topics = [pop().to_bytes(32, "big") for _ in range(n_topics)]
                use(G_LOG + G_LOG_TOPIC * n_topics + G_LOG_DATA * size)
                logs.append(Log(address, topics, mload(off, size)))
                continue

            if op == 0x00:  # STOP
                return b"", gas_left
            elif op == 0x01:  # ADD
                use(G_VERYLOW); push(pop() + pop())
            elif op == 0x02:  # MUL
                use(G_LOW); push(pop() * pop())
            elif op == 0x03:  # SUB
                use(G_VERYLOW); a = pop(); push(a - pop())
            elif op == 0x04:  # DIV
                use(G_LOW); a, b = pop(), pop(); push(a // b if b else 0)
            elif op == 0x05:  # SDIV
                use(G_LOW)
                a, b = _to_signed(pop()), _to_signed(pop())
                push(0 if b == 0 else abs(a) // abs(b) * (1 if a * b >= 0 else -1))
            elif op == 0x06:  # MOD
                use(G_LOW); a, b = pop(), pop(); push(a % b if b else 0)
            elif op == 0x07:  # SMOD
                use(G_LOW)
                a, b = _to_signed(pop()), _to_signed(pop())
                push(0 if b == 0 else abs(a) % abs(b) * (1 if a >= 0 else -1))
            elif op == 0x08:  # ADDMOD
                use(G_MID); a, b, n = pop(), pop(), pop()
                push((a + b) % n if n else 0)
            elif op == 0x09:  # MULMOD
                use(G_MID); a, b, n = pop(), pop(), pop()
                push(a * b % n if n else 0)
            elif op == 0x0A:  # EXP
                a, e = pop(), pop()
                use(G_EXP + G_EXP_BYTE * ((e.bit_length() + 7) // 8))
                push(pow(a, e, 1 << 256))
            elif op == 0x0B:  # SIGNEXTEND
                use(G_LOW)
                k, x = pop(), pop()
                if k < 31:
                    bit = 8 * (k + 1) - 1
                    if x & (1 << bit):
                        x |= U256 ^ ((1 << (bit + 1)) - 1)
                    else:
                        x &= (1 << (bit + 1)) - 1
                push(x)
            elif op == 0x10:  # LT
                use(G_VERYLOW); a = pop(); push(1 if a < pop() else 0)
            elif op == 0x11:  # GT
                use(G_VERYLOW); a = pop(); push(1 if a > pop() else 0)
            elif op == 0x12:  # SLT
                use(G_VERYLOW)
                a = _to_signed(pop()); push(1 if a < _to_signed(pop()) else 0)
            elif op == 0x13:  # SGT
                use(G_VERYLOW)
                a = _to_signed(pop()); push(1 if a > _to_signed(pop()) else 0)
            elif op == 0x14:  # EQ
                use(G_VERYLOW); push(1 if pop() == pop() else 0)
            elif op == 0x15:  # ISZERO
                use(G_VERYLOW); push(1 if pop() == 0 else 0)
            elif op == 0x16:  # AND
                use(G_VERYLOW); push(pop() & pop())
            elif op == 0x17:  # OR
                use(G_VERYLOW); push(pop() | pop())
            elif op == 0x18:  # XOR
                use(G_VERYLOW); push(pop() ^ pop())
            elif op == 0x19:  # NOT
                use(G_VERYLOW); push(~pop())
            elif op == 0x1A:  # BYTE
                use(G_VERYLOW); i, x = pop(), pop()
                push((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
            elif op == 0x1B:  # SHL
                use(G_VERYLOW); s, x = pop(), pop()
                push(x << s if s < 256 else 0)
            elif op == 0x1C:  # SHR
                use(G_VERYLOW); s, x = pop(), pop()
                push(x >> s if s < 256 else 0)
            elif op == 0x1D:  # SAR
                use(G_VERYLOW); s, x = pop(), _to_signed(pop())
                push(x >> s if s < 256 else (0 if x >= 0 else U256))
            elif op == 0x20:  # KECCAK256
                off, size = pop(), pop()
                use(G_KECCAK + G_KECCAK_WORD * (-(-size // 32)))
                push(int.from_bytes(keccak256(mload(off, size)), "big"))
            elif op == 0x30:  # ADDRESS
                use(G_BASE); push(int.from_bytes(address, "big"))
            elif op == 0x31:  # BALANCE
                use(G_SLOAD); push(self.balances.get(_addr(pop()), 0))
            elif op == 0x32:  # ORIGIN (≈ caller of the outer frame)
                use(G_BASE); push(int.from_bytes(caller, "big"))
            elif op == 0x33:  # CALLER
                use(G_BASE); push(int.from_bytes(caller, "big"))
            elif op == 0x34:  # CALLVALUE
                use(G_BASE); push(value)
            elif op == 0x35:  # CALLDATALOAD
                use(G_VERYLOW); off = pop()
                push(int.from_bytes(data[off : off + 32].ljust(32, b"\x00"), "big"))
            elif op == 0x36:  # CALLDATASIZE
                use(G_BASE); push(len(data))
            elif op == 0x37:  # CALLDATACOPY
                doff, off, size = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * (-(-size // 32)))
                mem_expand(doff, size)
                chunk = data[off : off + size].ljust(size, b"\x00")
                mem[doff : doff + size] = chunk
            elif op == 0x38:  # CODESIZE
                use(G_BASE); push(len(code))
            elif op == 0x39:  # CODECOPY
                doff, off, size = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * (-(-size // 32)))
                mem_expand(doff, size)
                chunk = code[off : off + size].ljust(size, b"\x00")
                mem[doff : doff + size] = chunk
            elif op == 0x3A:  # GASPRICE
                use(G_BASE); push(1)
            elif op == 0x3B:  # EXTCODESIZE
                use(G_SLOAD)
                push(len(self.accounts.get(_addr(pop()), EvmAccount()).code))
            elif op == 0x3C:  # EXTCODECOPY
                a, doff, off, size = pop(), pop(), pop(), pop()
                use(G_SLOAD + G_COPY_WORD * (-(-size // 32)))
                mem_expand(doff, size)
                xc = self.accounts.get(_addr(a), EvmAccount()).code
                mem[doff : doff + size] = xc[off : off + size].ljust(size, b"\x00")
            elif op == 0x3D:  # RETURNDATASIZE
                use(G_BASE); push(len(ret_data))
            elif op == 0x3E:  # RETURNDATACOPY
                doff, off, size = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * (-(-size // 32)))
                if off + size > len(ret_data):
                    raise _Fail("returndata out of bounds")
                mem_expand(doff, size)
                mem[doff : doff + size] = ret_data[off : off + size]
            elif op == 0x3F:  # EXTCODEHASH
                use(G_SLOAD)
                acct = self.accounts.get(_addr(pop()))
                push(
                    0 if acct is None
                    else int.from_bytes(keccak256(acct.code), "big")
                )
            elif op == 0x40:  # BLOCKHASH
                use(G_BASE * 10); pop(); push(0)
            elif op == 0x41:  # COINBASE
                use(G_BASE); push(0)
            elif op == 0x42:  # TIMESTAMP
                use(G_BASE)
                push(self.state.block_number * self.block_time_ms // 1000)
            elif op == 0x43:  # NUMBER
                use(G_BASE); push(self.state.block_number)
            elif op == 0x44:  # PREVRANDAO (the chain's shared randomness)
                use(G_BASE)
                push(int.from_bytes(self.state.randomness[:32], "big"))
            elif op == 0x45:  # GASLIMIT
                use(G_BASE); push(30_000_000)
            elif op == 0x46:  # CHAINID
                use(G_BASE); push(CHAIN_ID)
            elif op == 0x47:  # SELFBALANCE
                use(G_LOW); push(self.balances.get(address, 0))
            elif op == 0x48:  # BASEFEE
                use(G_BASE); push(1)
            elif op == 0x50:  # POP
                use(G_BASE); pop()
            elif op == 0x51:  # MLOAD
                use(G_VERYLOW); off = pop()
                push(int.from_bytes(mload(off, 32), "big"))
            elif op == 0x52:  # MSTORE
                use(G_VERYLOW); off, val = pop(), pop()
                mem_expand(off, 32)
                mem[off : off + 32] = val.to_bytes(32, "big")
            elif op == 0x53:  # MSTORE8
                use(G_VERYLOW); off, val = pop(), pop()
                mem_expand(off, 1)
                mem[off] = val & 0xFF
            elif op == 0x54:  # SLOAD
                use(G_SLOAD)
                push(self.storage.get((address, pop()), 0))
            elif op == 0x55:  # SSTORE
                if static:
                    raise _Fail("static sstore")
                slot, val = pop(), pop()
                cur = self.storage.get((address, slot), 0)
                use(
                    G_SSTORE_SET if cur == 0 and val != 0
                    else G_SSTORE_RESET
                )
                if val:
                    self.storage[(address, slot)] = val
                else:
                    self.storage.pop((address, slot), None)
            elif op == 0x56:  # JUMP
                use(G_MID); dest = pop()
                if dest not in jumpdests:
                    raise _Fail("bad jump")
                pc = dest + 1
            elif op == 0x57:  # JUMPI
                use(G_HIGH); dest, cond = pop(), pop()
                if cond:
                    if dest not in jumpdests:
                        raise _Fail("bad jump")
                    pc = dest + 1
            elif op == 0x58:  # PC
                use(G_BASE); push(pc - 1)
            elif op == 0x59:  # MSIZE
                use(G_BASE); push(len(mem))
            elif op == 0x5A:  # GAS
                use(G_BASE); push(gas_left)
            elif op == 0x5B:  # JUMPDEST
                use(1)
            elif op in (0xF0, 0xF5):  # CREATE / CREATE2
                if static:
                    raise _Fail("static create")
                val = pop(); off = pop(); size = pop()
                salt = pop().to_bytes(32, "big") if op == 0xF5 else None
                use(G_CREATE)
                init = mload(off, size)
                child_gas = gas_left - gas_left // 64
                use(child_gas)
                snap = self._snapshot()
                sub_logs: list[Log] = []
                try:
                    me = self.accounts.setdefault(address, EvmAccount())
                    my_nonce = me.nonce
                    me.nonce += 1
                    new_addr, sub_left = self._create_frame(
                        address, init, val, child_gas, sub_logs,
                        depth + 1, salt=salt, nonce=my_nonce,
                    )
                    logs.extend(sub_logs)
                    gas_left += sub_left
                    ret_data = b""
                    push(int.from_bytes(new_addr, "big"))
                except _Revert as rv:
                    self._restore(snap)
                    ret_data = rv.data
                    push(0)
                except _Fail:
                    self._restore(snap)
                    ret_data = b""
                    push(0)
            elif op in (0xF1, 0xF4, 0xFA):  # CALL/DELEGATECALL/STATICCALL
                req_gas = pop()
                to = _addr(pop())
                val = pop() if op == 0xF1 else 0
                in_off, in_size = pop(), pop()
                out_off, out_size = pop(), pop()
                cost = G_CALL
                if val:
                    cost += G_CALL_VALUE
                    if to not in self.accounts and to not in self.balances:
                        cost += G_NEW_ACCOUNT
                use(cost)
                arg = mload(in_off, in_size)
                mem_expand(out_off, out_size)
                avail = gas_left - gas_left // 64
                child_gas = min(req_gas, avail)
                use(child_gas)
                if val:
                    child_gas += 2300  # value-call stipend
                snap = self._snapshot()
                sub_logs = []
                try:
                    if op == 0xF4:  # DELEGATECALL: callee code, our ctx
                        out, sub_left = self._call_frame(
                            caller, address, arg, value, child_gas,
                            sub_logs, static, depth + 1,
                            code_addr=to, ctx_addr=address,
                        )
                    elif op == 0xFA:  # STATICCALL
                        out, sub_left = self._call_frame(
                            address, to, arg, 0, child_gas, sub_logs,
                            True, depth + 1,
                        )
                    else:
                        out, sub_left = self._call_frame(
                            address, to, arg, val, child_gas, sub_logs,
                            static, depth + 1,
                        )
                    logs.extend(sub_logs)
                    gas_left += sub_left
                    ret_data = out
                    mem[out_off : out_off + out_size] = out[:out_size].ljust(
                        out_size, b"\x00"
                    )
                    push(1)
                except _Revert as rv:
                    self._restore(snap)
                    ret_data = rv.data
                    mem[out_off : out_off + out_size] = rv.data[
                        :out_size
                    ].ljust(out_size, b"\x00")
                    push(0)
                except _Fail:
                    self._restore(snap)
                    ret_data = b""
                    push(0)
            elif op == 0xF3:  # RETURN
                off, size = pop(), pop()
                return mload(off, size), gas_left
            elif op == 0xFD:  # REVERT
                off, size = pop(), pop()
                raise _Revert(mload(off, size))
            elif op == 0xFE:  # INVALID
                raise _Fail("invalid opcode")
            elif op == 0xFF:  # SELFDESTRUCT
                if static:
                    raise _Fail("static selfdestruct")
                use(5000)
                heir = _addr(pop())
                bal = self.balances.pop(address, 0)
                if bal:
                    self.balances[heir] = self.balances.get(heir, 0) + bal
                self.accounts.pop(address, None)
                return b"", gas_left
            else:
                raise _Fail(f"unknown opcode 0x{op:02x}")
        return b"", gas_left


def _jumpdests(code: bytes) -> frozenset[int]:
    """Valid JUMPDEST offsets (PUSH immediates are not destinations)."""
    out = set()
    i = 0
    while i < len(code):
        op = code[i]
        if op == 0x5B:
            out.add(i)
        i += 1 + (op - 0x5F if 0x60 <= op <= 0x7F else 0)
    return frozenset(out)
