"""User space market: buy / expand / renew leases; global space counters.

Re-design of the reference storage-handler pallet (reference:
c-pallets/storage-handler/src/lib.rs).  Semantics preserved:

 * buy_space: gib_count GiB for 30 days at UnitPrice per GiB-month, paid to
   the file-bank pot (lib.rs:175-200);
 * expansion_space: extra GiB pro-rated at the daily unit price over the
   remaining lease days, rounded up to whole days (lib.rs:208-269);
 * renewal_space: extend the lease by N days for total_space GiB at the
   daily price (lib.rs:273-311);
 * user ledger: total/used/locked/remaining with lock → use/unlock flows
   driven by file-bank deals (lib.rs:520-560);
 * global counters: TotalIdleSpace / TotalServiceSpace / PurchasedSpace with
   the "cannot sell more than the network holds" check (lib.rs:595-618);
 * frozen_task: lease-expiry sweep — frozen after deadline, dead (files
   cleared by file-bank) after deadline + FrozenDays (lib.rs:458-519).
"""

from __future__ import annotations

from dataclasses import dataclass

from .state import ChainState
from .types import AccountId, Balance, BlockNumber, G_BYTE, ensure

MOD = "storage_handler"

SPACE_NORMAL = "normal"
SPACE_FROZEN = "frozen"
SPACE_DEAD = "dead"

FILBAK_POT = "pot/filbak"


@dataclass
class OwnedSpaceDetails:
    """reference: storage-handler/src/types.rs:6-13"""

    total_space: int
    used_space: int
    locked_space: int
    remaining_space: int
    start: BlockNumber
    deadline: BlockNumber
    state: str


class StorageHandlerPallet:
    def __init__(
        self,
        state: ChainState,
        one_day_block: int,
        frozen_days: int,
        unit_price: Balance,
    ) -> None:
        self.state = state
        self.one_day_block = one_day_block
        self.frozen_days_blocks = frozen_days * one_day_block
        self.unit_price = unit_price  # price of 1 GiB for 30 days
        self.user_owned_space: dict[AccountId, OwnedSpaceDetails] = {}
        self.total_idle_space: int = 0
        self.total_service_space: int = 0
        self.purchased_space: int = 0

    # ---------------------------------------------------------------- calls

    def buy_space(self, sender: AccountId, gib_count: int) -> None:
        """reference: lib.rs:175-200"""
        ensure(sender not in self.user_owned_space, MOD, "PurchasedSpace")
        space = G_BYTE * gib_count
        price = self.unit_price * gib_count
        # Checks-first (the reference relies on #[transactional] rollback to
        # recover from its mutate-then-check order; we must not mutate until
        # every check has passed).
        ensure(
            self.state.balances.can_slash(sender, price), MOD, "InsufficientBalance"
        )
        total = self.total_idle_space + self.total_service_space
        ensure(
            self.purchased_space + space <= total, MOD, "InsufficientAvailableSpace"
        )
        self._add_user_purchased_space(sender, space, days=30)
        self._add_purchased_space(space)
        self.state.balances.transfer(sender, FILBAK_POT, price)
        self.state.deposit_event(
            MOD, "BuySpace", acc=sender, storage_capacity=space, spend=price
        )

    def expansion_space(self, sender: AccountId, gib_count: int) -> None:
        """reference: lib.rs:208-269"""
        info = self._space(sender)
        now = self.state.block_number
        ensure(now < info.deadline, MOD, "LeaseExpired")
        ensure(info.state != SPACE_FROZEN, MOD, "LeaseFreeze")
        day_unit_price = self.unit_price // 30
        space = G_BYTE * gib_count
        diff_block = info.deadline - now
        remain_day = diff_block // self.one_day_block
        if diff_block % self.one_day_block != 0:
            remain_day += 1
        price = day_unit_price * gib_count * remain_day
        ensure(
            self.state.balances.can_slash(sender, price), MOD, "InsufficientBalance"
        )
        self._add_purchased_space(space)
        info.remaining_space += space
        info.total_space += space
        self.state.balances.transfer(sender, FILBAK_POT, price)
        self.state.deposit_event(
            MOD, "ExpansionSpace", acc=sender, expansion_space=space, fee=price
        )

    def renewal_space(self, sender: AccountId, days: int) -> None:
        """reference: lib.rs:273-311"""
        info = self._space(sender)
        ensure(info.state != SPACE_DEAD, MOD, "LeaseExpired")
        day_unit_price = self.unit_price // 30
        gib_count = info.total_space // G_BYTE
        price = day_unit_price * gib_count * days
        ensure(
            self.state.balances.can_slash(sender, price), MOD, "InsufficientBalance"
        )
        self.state.balances.transfer(sender, FILBAK_POT, price)
        # update_puchased_package (reference: lib.rs:334-359)
        now = self.state.block_number
        sur_block = self.one_day_block * days
        if now > info.deadline:
            info.start = now
            info.deadline = now + sur_block
        else:
            info.deadline += sur_block
        if info.deadline > now:
            info.state = SPACE_NORMAL
        self.state.deposit_event(
            MOD, "RenewalSpace", acc=sender, renewal_days=days, fee=price
        )

    def update_price(self, new_price: Balance) -> None:
        """Root call (reference: lib.rs:314-321)."""
        self.unit_price = new_price

    # ------------------------------------------------------------ internals

    def _space(self, acc: AccountId) -> OwnedSpaceDetails:
        info = self.user_owned_space.get(acc)
        ensure(info is not None, MOD, "NotPurchasedSpace", acc)
        return info

    def _add_user_purchased_space(
        self, acc: AccountId, space: int, days: int
    ) -> None:
        now = self.state.block_number
        self.user_owned_space[acc] = OwnedSpaceDetails(
            total_space=space,
            used_space=0,
            locked_space=0,
            remaining_space=space,
            start=now,
            deadline=now + self.one_day_block * days,
            state=SPACE_NORMAL,
        )

    def _add_purchased_space(self, size: int) -> None:
        total = self.total_idle_space + self.total_service_space
        ensure(
            self.purchased_space + size <= total, MOD, "InsufficientAvailableSpace"
        )
        self.purchased_space += size

    # -- StorageHandle trait (reference: lib.rs:622-637) ----------------

    def update_user_space(self, acc: AccountId, operation: int, size: int) -> None:
        info = self._space(acc)
        if operation == 1:
            ensure(info.state != SPACE_FROZEN, MOD, "LeaseFreeze")
            ensure(size <= info.remaining_space, MOD, "InsufficientStorage")
            info.used_space += size
            info.remaining_space -= size
        elif operation == 2:
            ensure(info.used_space >= size, MOD, "Overflow")
            info.used_space -= size
            info.remaining_space = info.total_space - info.used_space
        else:
            ensure(False, MOD, "WrongOperation")

    def lock_user_space(self, acc: AccountId, needed_space: int) -> None:
        info = self._space(acc)
        ensure(info.state != SPACE_FROZEN, MOD, "LeaseFreeze")
        ensure(info.remaining_space >= needed_space, MOD, "InsufficientStorage")
        info.locked_space += needed_space
        info.remaining_space -= needed_space

    def unlock_user_space(self, acc: AccountId, needed_space: int) -> None:
        info = self._space(acc)
        ensure(info.locked_space >= needed_space, MOD, "Overflow")
        info.locked_space -= needed_space
        info.remaining_space += needed_space

    def unlock_and_used_user_space(self, acc: AccountId, needed_space: int) -> None:
        info = self._space(acc)
        ensure(info.locked_space >= needed_space, MOD, "Overflow")
        info.locked_space -= needed_space
        info.used_space += needed_space

    def get_user_avail_space(self, acc: AccountId) -> int:
        return self._space(acc).remaining_space

    def check_user_space(self, acc: AccountId, needed_space: int) -> bool:
        return self._space(acc).remaining_space >= needed_space

    def get_total_space(self) -> int:
        total = self.total_idle_space + self.total_service_space
        if total < self.purchased_space:
            return 0
        return total - self.purchased_space

    def add_total_idle_space(self, increment: int) -> None:
        self.total_idle_space += increment

    def sub_total_idle_space(self, decrement: int) -> None:
        ensure(self.total_idle_space >= decrement, MOD, "Overflow")
        self.total_idle_space -= decrement

    def add_total_service_space(self, increment: int) -> None:
        self.total_service_space += increment

    def sub_total_service_space(self, decrement: int) -> None:
        ensure(self.total_service_space >= decrement, MOD, "Overflow")
        self.total_service_space -= decrement

    def add_purchased_space(self, size: int) -> None:
        self._add_purchased_space(size)

    def sub_purchased_space(self, size: int) -> None:
        ensure(self.purchased_space >= size, MOD, "Overflow")
        self.purchased_space -= size

    def delete_user_space_storage(self, acc: AccountId) -> None:
        """reference: lib.rs:698-712 — release the purchased allotment and
        drop the user's ledger entry (file cleanup is file-bank's job)."""
        info = self._space(acc)
        self.sub_purchased_space(info.total_space)
        del self.user_owned_space[acc]

    # -- lease-expiry sweep ---------------------------------------------

    def frozen_task(self) -> list[AccountId]:
        """Block sweep (reference: lib.rs:458-519): past deadline → frozen;
        past deadline + FrozenDays → dead, returned for file clearing."""
        now = self.state.block_number
        clear_list: list[AccountId] = []
        for acc, info in sorted(self.user_owned_space.items()):
            if now > info.deadline:
                if now > info.deadline + self.frozen_days_blocks:
                    info.state = SPACE_DEAD
                    clear_list.append(acc)
                elif info.state != SPACE_FROZEN:
                    info.state = SPACE_FROZEN
        return clear_list
