"""PoDR2 random-challenge audit engine ("segment book").

Re-design of the reference audit pallet (reference:
c-pallets/audit/src/{lib,types,constants}.rs).  The protocol round:

 1. Validators' offchain workers each derive the *identical* challenge from
    shared randomness (~10% of miners, 47 chunk indices, 47 20-byte
    coefficients) and vote via unsigned extrinsics; a 2/3 quorum over the
    hash of the canonically-encoded challenge commits the snapshot
    (lib.rs:364-416, 846-940).
 2. Challenged miners submit σ/μ proofs before the challenge deadline; each
    proof batch is scattered to a random TEE worker (lib.rs:418-470).
 3. TEEs verify off-chain — in this framework through the ProofBackend
    (TPU-batched PoDR2) — and report two booleans; pass mints a reward order,
    double-fail punishes idle 10% / service 25% (lib.rs:472-535).
 4. Block sweeps escalate: silent miners suffer 30/60/100% clear punishment
    and forced exit at 3 strikes; late TEEs are slashed and their batch is
    reassigned to another TEE (lib.rs:559-682).

Unlike the reference (whose on-chain check is a declared TODO at
lib.rs:484), `submit_verify_result` here *does* verify the TEE result
signature against the worker's registered node key.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..utils import codec
from ..utils.hashing import sha256
from ..utils.rng import ProtocolRng
from .state import ChainState
from .types import AccountId, BlockNumber, DispatchError, ensure

MOD = "audit"

# reference: audit/src/constants.rs:1-3
IDLE_FAULT_TOLERANT = 2
SERVICE_FAULT_TOLERANT = 2

# reference: runtime/src/lib.rs:986-996
CHALLENGE_MINER_MAX = 8000
VERIFY_MISSION_MAX = 500
SIGMA_MAX = 2048

CHUNK_COUNT = 1024  # reference: primitives/common/src/lib.rs:62
U64_LIMIT = (1 << 64) - 1


@dataclass
class MinerSnapShot:
    """reference: audit/src/types.rs:25-30"""

    miner: AccountId
    idle_space: int
    service_space: int

    def encode(self) -> bytes:
        return (
            codec.Writer()
            .bytes(self.miner.encode())
            .u128(self.idle_space)
            .u128(self.service_space)
            .finish()
        )


@dataclass
class NetSnapShot:
    """reference: audit/src/types.rs:14-23"""

    start: BlockNumber
    life: BlockNumber
    total_reward: int
    total_idle_space: int
    total_service_space: int
    random_index_list: list[int]
    random_list: list[bytes]  # 20-byte coefficients

    def encode(self) -> bytes:
        w = (
            codec.Writer()
            .u32(self.start)
            .u32(self.life)
            .u128(self.total_reward)
            .u128(self.total_idle_space)
            .u128(self.total_service_space)
        )
        w.compact(len(self.random_index_list))
        for i in self.random_index_list:
            w.u32(i)
        w.compact(len(self.random_list))
        for r in self.random_list:
            w.raw(r)
        return w.finish()


@dataclass
class ChallengeInfo:
    """reference: audit/src/types.rs:6-12"""

    net_snap_shot: NetSnapShot
    miner_snapshot_list: list[MinerSnapShot]

    def encode(self) -> bytes:
        """Canonical encoding — the quorum hashes this, so every validator
        must produce identical bytes (reference: lib.rs:376-378)."""
        w = codec.Writer().raw(self.net_snap_shot.encode())
        w.compact(len(self.miner_snapshot_list))
        for m in self.miner_snapshot_list:
            w.raw(m.encode())
        return w.finish()

    def proposal_hash(self) -> bytes:
        return sha256(self.encode())


@dataclass
class ProveInfo:
    """reference: audit/src/types.rs:33-41"""

    snap_shot: MinerSnapShot
    idle_prove: bytes
    service_prove: bytes


class AuditPallet:
    def __init__(
        self,
        state: ChainState,
        sminer,
        file_bank,
        tee_worker,
        one_day_block: int = 14400,
        one_hour_block: int = 600,
        lock_time: int = 10,
        result_verifier: Callable | None = None,
        chunk_count: int = CHUNK_COUNT,
    ) -> None:
        self.state = state
        self.sminer = sminer
        self.file_bank = file_bank
        self.tee_worker = tee_worker
        self.one_day_block = one_day_block
        self.one_hour_block = one_hour_block
        self.lock_time = lock_time
        # Scheme geometry: chunks per fragment (protocol value 1024,
        # reference primitives/common/src/lib.rs:62; scaled down in sims).
        self.chunk_count = chunk_count
        # verify(tee_node_key, message, signature) -> bool for
        # submit_verify_result; None disables (test mode).
        self.result_verifier = result_verifier

        self.challenge_duration: BlockNumber = 0
        self.verify_duration: BlockNumber = 0
        self.keys: list[AccountId] = []  # validator authority keys
        self.challenge_proposal: dict[bytes, tuple[int, ChallengeInfo]] = {}
        # Replay guard: the reference gets per-(session, key) uniqueness from
        # the unsigned-tx pool's `and_provides` tag (lib.rs:705); we track
        # which authorities voted which proposal explicitly.
        self.proposal_voters: dict[bytes, set[AccountId]] = {}
        self.challenge_snap_shot: ChallengeInfo | None = None
        self.unverify_proof: dict[AccountId, list[ProveInfo]] = {}
        self.counted_idle_failed: dict[AccountId, int] = {}
        self.counted_service_failed: dict[AccountId, int] = {}
        self.counted_clear: dict[AccountId, int] = {}
        # Offchain-worker local lock (per authority), reference lib.rs:782-816.
        self._ocw_lock: dict[AccountId, BlockNumber] = {}

    # ------------------------------------------------------------ randomness

    def random_number(self, seed: int) -> int:
        """u64 from (shared randomness, pallet id, seed) (reference:
        lib.rs:1019-1032)."""
        return ProtocolRng(self.state.randomness + b"rewardpt", domain=seed).u64()

    def generate_challenge_random(self, seed: int) -> bytes:
        """20-byte challenge coefficient (reference: lib.rs:1035-1048)."""
        rng = ProtocolRng(self.state.randomness + b"rewardpt:r", domain=seed + 1)
        return rng.take(20)

    # ------------------------------------------------------------ hooks

    def on_initialize(self, now: BlockNumber) -> None:
        self.clear_challenge(now)
        self.clear_verify_mission(now)

    def clear_challenge(self, now: BlockNumber) -> None:
        """Challenge deadline sweep (reference: lib.rs:559-600): every miner
        still in the snapshot is silent — escalate 30/60/100% and force exit
        at 3 strikes."""
        if now != self.challenge_duration:
            return
        snap_shot = self.challenge_snap_shot
        if snap_shot is None:
            return
        for miner_snapshot in snap_shot.miner_snapshot_list:
            count = self.counted_clear.get(miner_snapshot.miner, 0) + 1
            try:
                self.sminer.clear_punish(
                    miner_snapshot.miner,
                    count,
                    miner_snapshot.idle_space,
                    miner_snapshot.service_space,
                )
            except DispatchError:
                pass
            if count >= 3:
                try:
                    self.file_bank.force_miner_exit(miner_snapshot.miner)
                except DispatchError:
                    pass
                self.counted_clear.pop(miner_snapshot.miner, None)
            else:
                self.counted_clear[miner_snapshot.miner] = count

    def clear_verify_mission(self, now: BlockNumber) -> None:
        """Verify deadline sweep (reference: lib.rs:602-682): late TEEs are
        slashed + credit-punished, their batches reassigned to another random
        TEE; an empty round kills the snapshot."""
        if now != self.verify_duration:
            return
        seed = 0
        mission_count = 0
        tee_list = self.tee_worker.get_controller_list()
        reassign_list: dict[AccountId, list[ProveInfo]] = {}
        for acc in sorted(self.unverify_proof):
            unverify_list = self.unverify_proof[acc]
            seed += 1
            if len(unverify_list) > 0:
                try:
                    self.tee_worker.punish_scheduler(acc)
                except DispatchError:
                    pass
                mission_count += len(unverify_list)
                index = self.random_number(seed) % len(tee_list)
                tee_acc = tee_list[index]
                if acc == tee_acc:
                    index = (index + 1) % len(tee_list)
                    tee_acc = tee_list[index]
                reassign_list.setdefault(tee_acc, []).extend(unverify_list)
        for acc in list(self.unverify_proof):
            if self.unverify_proof[acc]:
                del self.unverify_proof[acc]

        if mission_count == 0:
            self.challenge_snap_shot = None
        else:
            for acc, unverify_list in sorted(reassign_list.items()):
                self.unverify_proof.setdefault(acc, []).extend(unverify_list)
            self.verify_duration = now + mission_count * 10

    # ------------------------------------------------------------ quorum

    def save_challenge_info(
        self,
        challenge_info: ChallengeInfo,
        key: AccountId,
        signature,
        signature_checker: Callable | None = None,
    ) -> None:
        """Unsigned extrinsic: one validator's challenge vote.  2/3 of the
        authority set agreeing on the hash commits the round (reference:
        lib.rs:364-416, validate_unsigned at 540-556, 684-717)."""
        # validate_unsigned equivalent
        ensure(key in self.keys, MOD, "InvalidUnsigned", "stale key")
        if signature_checker is not None:
            ensure(
                signature_checker(key, challenge_info, signature),
                MOD,
                "InvalidUnsigned",
                "bad proof",
            )

        h = challenge_info.proposal_hash()
        count = len(self.keys)
        # 2/3 supermajority, rounded UP (same threshold as the finality
        # gadget's sync.quorum — floor division would let 1 of 2 or 2 of
        # 4 authorities commit a round alone).  ceil(2n/3) is 1 for a
        # single-authority dev chain, so its own vote still commits.
        limit = max((2 * count + 2) // 3, 1)
        ensure(
            key not in self.proposal_voters.get(h, set()),
            MOD,
            "InvalidUnsigned",
            "duplicate vote",
        )
        # Stale-proposal purge, loose on purpose: under a lossy network
        # (the chaos soak, node/faults.py) validators' votes for one
        # trigger block arrive staggered across several blocks, and a
        # purge bound of `count` wiped forming tallies faster than
        # quorum could meet — the round then never commits.  4× keeps
        # state bounded while letting a staggered quorum land.
        if h not in self.challenge_proposal and len(
            self.challenge_proposal
        ) > 4 * count:
            self.challenge_proposal.clear()
            self.proposal_voters.clear()
        self.proposal_voters.setdefault(h, set()).add(key)
        votes, info = self.challenge_proposal.get(h, (0, challenge_info))
        votes += 1
        self.challenge_proposal[h] = (votes, info)
        if votes >= limit:
            now = self.state.block_number
            if now > self.challenge_duration:
                self.challenge_snap_shot = info
                duration = now + info.net_snap_shot.life
                self.challenge_duration = duration
                self.verify_duration = (
                    duration + info.net_snap_shot.life + self.one_hour_block
                )
                self.challenge_proposal.clear()
                self.proposal_voters.clear()
            self.state.deposit_event(MOD, "GenerateChallenge")

    # ------------------------------------------------------------ proofs

    def submit_proof(
        self, sender: AccountId, idle_prove: bytes, service_prove: bytes
    ) -> None:
        """Challenged miner hands in its σ proofs; batch lands on a random
        TEE (reference: lib.rs:418-470)."""
        ensure(len(idle_prove) <= SIGMA_MAX, MOD, "LengthExceedsLimit")
        ensure(len(service_prove) <= SIGMA_MAX, MOD, "LengthExceedsLimit")
        challenge = self.challenge_snap_shot
        ensure(challenge is not None, MOD, "NoChallenge")
        # Checks-first: resolve the target TEE and capacity before touching
        # the snapshot, so a failed call leaves the audit obligation intact.
        pop_index = None
        for index, snap in enumerate(challenge.miner_snapshot_list):
            if snap.miner == sender:
                now = self.state.block_number
                ensure(now < self.challenge_duration, MOD, "NoChallenge")
                pop_index = index
                break
        ensure(pop_index is not None, MOD, "NoChallenge")

        tee_list = self.tee_worker.get_controller_list()
        ensure(len(tee_list) > 0, MOD, "SystemError")
        seed = self.state.block_number
        index = self.random_number(seed) % len(tee_list)
        tee_acc = tee_list[index]
        missions = self.unverify_proof.setdefault(tee_acc, [])
        ensure(len(missions) < VERIFY_MISSION_MAX, MOD, "Overflow")

        miner_snapshot = challenge.miner_snapshot_list.pop(pop_index)
        self.counted_clear[sender] = 0
        missions.append(
            ProveInfo(
                snap_shot=miner_snapshot,
                idle_prove=bytes(idle_prove),
                service_prove=bytes(service_prove),
            )
        )
        self.state.deposit_event(MOD, "SubmitProof", miner=sender)

    @staticmethod
    def result_message(
        miner: AccountId, idle_result: bool, service_result: bool
    ) -> bytes:
        """Canonical bytes a TEE signs over its verdict."""
        return (
            codec.Writer()
            .bytes(miner.encode())
            .boolean(idle_result)
            .boolean(service_result)
            .finish()
        )

    def submit_verify_result(
        self,
        sender: AccountId,
        miner: AccountId,
        idle_result: bool,
        service_result: bool,
        tee_signature: bytes = b"",
    ) -> None:
        """TEE verdict for one miner's batch (reference: lib.rs:472-535).
        Both pass → reward order; fail twice running → idle/service punish.
        The TEE signature is checked against the registered node key (the
        seam the reference leaves as TODO at lib.rs:484)."""
        if self.result_verifier is not None:
            worker = self.tee_worker.tee_worker_map.get(sender)
            ensure(worker is not None, MOD, "NonExistentMission")
            ensure(
                self.result_verifier(
                    worker.node_key,
                    self.result_message(miner, idle_result, service_result),
                    tee_signature,
                ),
                MOD,
                "VerifyTeeSigFailed",
            )
        unverify_list = self.unverify_proof.get(sender, [])
        for index, miner_info in enumerate(unverify_list):
            if miner_info.snap_shot.miner != miner:
                continue
            snap_shot = self.challenge_snap_shot
            ensure(snap_shot is not None, MOD, "UnexpectedError")

            if idle_result and service_result:
                self.sminer.calculate_miner_reward(
                    miner,
                    snap_shot.net_snap_shot.total_reward,
                    snap_shot.net_snap_shot.total_idle_space,
                    snap_shot.net_snap_shot.total_service_space,
                    miner_info.snap_shot.idle_space,
                    miner_info.snap_shot.service_space,
                )

            if idle_result:
                self.counted_idle_failed[miner] = 0
            else:
                count = self.counted_idle_failed.get(miner, 0) + 1
                if count >= IDLE_FAULT_TOLERANT:
                    self.sminer.idle_punish(
                        miner,
                        miner_info.snap_shot.idle_space,
                        miner_info.snap_shot.service_space,
                    )
                self.counted_idle_failed[miner] = count

            if service_result:
                self.counted_service_failed[miner] = 0
            else:
                count = self.counted_service_failed.get(miner, 0) + 1
                if count >= SERVICE_FAULT_TOLERANT:
                    self.sminer.service_punish(
                        miner,
                        miner_info.snap_shot.idle_space,
                        miner_info.snap_shot.service_space,
                    )
                self.counted_service_failed[miner] = count

            unverify_list.pop(index)
            self.state.deposit_event(
                MOD, "VerifyProof", tee_worker=sender, miner=miner
            )
            return
        raise DispatchError(MOD, "NonExistentMission")

    # ------------------------------------------------------------ offchain

    def trigger_challenge(self, now: BlockNumber) -> bool:
        """≈once-a-day probability window (reference: lib.rs:739-757)."""
        time_point = self.random_number(20220509)
        probability = self.one_day_block
        window = U64_LIMIT // probability * 10
        return 2190502 < time_point < window + 2190502

    def check_working(self, now: BlockNumber, authority: AccountId) -> bool:
        """Offchain local lock (reference: lib.rs:782-816)."""
        last = self._ocw_lock.get(authority)
        if last is not None and last + self.lock_time > now:
            return False
        self._ocw_lock[authority] = now
        return True

    def unlock_offchain(self, authority: AccountId) -> None:
        self._ocw_lock.pop(authority, None)

    def offchain_worker(
        self,
        now: BlockNumber,
        authority: AccountId,
        submit: Callable | None = None,
    ):
        """One validator's OCW pass: maybe generate + vote a challenge
        (reference: lib.rs:342-359, 759-780).  Returns the ChallengeInfo it
        voted (for tests), else None.

        `submit` is the transaction-submission seam (the reference's
        SubmitTransaction::submit_unsigned_transaction): when given, the
        vote is handed to it (a live node routes it through its own tx
        pool so every replica applies it in block order) instead of being
        written into local state directly (the in-process sim path)."""
        if now <= self.verify_duration:
            return None
        if not self.trigger_challenge(now):
            return None
        if authority not in self.keys:
            return None
        if not self.check_working(now, authority):
            return None
        try:
            info = self.generation_challenge(now)
        except DispatchError:
            self.unlock_offchain(authority)
            return None
        if submit is not None:
            submit(info)
        else:
            self.save_challenge_info(info, authority, signature=None)
        self.unlock_offchain(authority)
        return info

    def generation_challenge(self, now: BlockNumber) -> ChallengeInfo:
        """Derive the round's challenge deterministically from shared
        randomness (reference: lib.rs:846-940): sample ⌈10%⌉ miners
        (skipping locked/empty ones), snapshot their spaces, then draw 47
        distinct chunk indices and 47 distinct 20-byte coefficients."""
        miner_count = self.sminer.get_miner_count()
        ensure(miner_count != 0, MOD, "GenerateInfoError")
        need_miner_count = miner_count // 10 + 1

        miner_list: list[MinerSnapShot] = []
        valid_index_list: list[int] = []
        total_idle_space = 0
        total_service_space = 0
        max_space = 0
        seed = 20230601
        while (
            len(miner_list) != need_miner_count
            and len(valid_index_list) != miner_count
        ):
            seed += 1
            index_list = self.random_select_miner(
                need_miner_count, miner_count, valid_index_list, seed
            )
            allminer = self.sminer.get_all_miner()
            for index in index_list:
                valid_index_list.append(index)
                miner = allminer[index]
                if self.sminer.get_miner_state(miner) == "lock":
                    continue
                idle_space, service_space = self.sminer.get_power(miner)
                if idle_space == 0 and service_space == 0:
                    continue
                max_space = max(max_space, idle_space + service_space)
                total_idle_space += idle_space
                total_service_space += service_space
                miner_list.append(
                    MinerSnapShot(
                        miner=miner,
                        idle_space=idle_space,
                        service_space=service_space,
                    )
                )
                if len(miner_list) > CHALLENGE_MINER_MAX:
                    raise DispatchError(MOD, "GenerateInfoError")

        # An empty snapshot would commit a round nobody can answer and
        # stall the audit until verify_duration passes — no challenge
        # without at least one challengeable (powered, unlocked) miner.
        ensure(len(miner_list) > 0, MOD, "GenerateInfoError")

        # 46/1000 density: 47 of 1024 (reference: audit/src/lib.rs:906).
        need_count = max(1, self.chunk_count * 46 // 1000)
        random_index_list: list[int] = []
        seed = 0
        while len(random_index_list) < need_count:
            seed += 1
            random_index = self.random_number(seed) % self.chunk_count
            if random_index not in random_index_list:
                random_index_list.append(random_index)

        random_list: list[bytes] = []
        seed = now
        while len(random_list) < need_count:
            seed += 1
            random_number = self.generate_challenge_random(seed)
            if random_number not in random_list:
                random_list.append(random_number)

        life = max_space // 8_947_849 + 12  # reference: lib.rs:926
        total_reward = self.sminer.get_reward()
        return ChallengeInfo(
            net_snap_shot=NetSnapShot(
                start=now,
                life=life,
                total_reward=total_reward,
                total_idle_space=total_idle_space,
                total_service_space=total_service_space,
                random_index_list=random_index_list,
                random_list=random_list,
            ),
            miner_snapshot_list=miner_list,
        )

    def random_select_miner(
        self, need: int, length: int, valid_index_list: list[int], seed: int
    ) -> list[int]:
        """reference: lib.rs:942-961 — rejection-sample distinct, unseen
        miner indices."""
        miner_index_list: list[int] = []
        seed = seed * 1000
        while len(miner_index_list) < need and (
            len(valid_index_list) + len(miner_index_list) != length
        ):
            seed += 1
            index = self.random_number(seed) % length
            if index in valid_index_list:
                continue
            if index not in miner_index_list:
                miner_index_list.append(index)
        return miner_index_list

    def initialize_keys(self, keys: list[AccountId]) -> None:
        if keys:
            assert not self.keys, "Keys are already initialized!"
            self.keys = list(keys)
