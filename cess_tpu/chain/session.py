"""Session pallet: keyed authority sessions driving era rotation.

Role match: stock `pallet_session` + `pallet_session::historical` as the
reference wires them (runtime/src/lib.rs:1484-1527, session keys feeding
the RRSC/GRANDPA/im-online authority sets; SessionsPerEra = 6 with 1 h
epochs, runtime/src/lib.rs:245).  Collapsed onto this framework's
deterministic runtime:

 * accounts register session keys (`set_keys`/`purge_keys` — the opaque
   SessionKeys blob role; here a single BLS public key per authority);
 * the session index advances every `session_length` blocks; every
   `sessions_per_era`-th rotation applies the pending OFFENCES
   (chain/offences.py — convictions defer to the era boundary so every
   replica slashes in the same block), ends the staking era, and runs
   the credit-weighted RRSC election (chain/rrsc.py) — which then
   already sees the fresh chills;
 * each rotation records the validator-set digest AND the set itself in
   `historical` / `historical_validators` (the
   pallet_session::historical root used for offence proofs: a report
   naming session s is only accepted if its offender was an authority
   in s) and notifies registered observers — the offences pallet's
   im-online liveness sweep rides this hook.
"""

from __future__ import annotations

import hashlib

from .state import ChainState
from .types import AccountId, ensure

MOD = "session"

# Sessions kept in `historical` / `historical_validators`: offence
# evidence older than this can no longer prove set membership and is
# refused (offences.REPORT_HISTORY_SESSIONS derives from this).
HISTORY_DEPTH_SESSIONS = 84


class SessionPallet:
    def __init__(
        self,
        state: ChainState,
        staking,
        rrsc,
        session_length: int,
        sessions_per_era: int = 6,
        offences=None,
    ) -> None:
        self.state = state
        self.staking = staking
        self.rrsc = rrsc
        self.offences = offences
        self.session_length = max(1, session_length)
        self.sessions_per_era = max(1, sessions_per_era)
        self.session_index: int = 0
        self.keys: dict[AccountId, bytes] = {}
        # session index -> hex digest of the active validator set (the
        # historical-root role for offence proofs) + the set itself
        # (membership checks for evidence-backed reports)
        self.historical: dict[int, str] = {}
        self.historical_validators: dict[int, list] = {}
        self._observers: list = []  # on_new_session(index, validators)

    # ------------------------------------------------------------ keys

    def set_keys(self, sender: AccountId, keys: bytes) -> None:
        """Register an authority's session keys (stock set_keys; the
        reference requires a bonded controller — same gate here)."""
        ensure(len(keys) > 0, MOD, "EmptyKeys")
        ensure(
            sender in self.staking.ledger or sender in self.staking.bonded.values(),
            MOD, "NoAssociatedValidatorId",
        )
        self.keys[sender] = bytes(keys)
        self.state.deposit_event(MOD, "KeysSet", who=sender)

    def purge_keys(self, sender: AccountId) -> None:
        ensure(sender in self.keys, MOD, "NoKeys")
        del self.keys[sender]
        self.state.deposit_event(MOD, "KeysPurged", who=sender)

    # ------------------------------------------------------------ views

    def session_of_block(self, height: int) -> int:
        """The session a block height executed in (rotations happen in
        the on_initialize of every session_length-th block, so block h
        belongs to session h // session_length) — the deterministic
        anchor that pins offence evidence to one session on every
        replica."""
        return max(0, int(height)) // self.session_length

    def validators_at(self, session: int) -> list | None:
        """Authority set of a (possibly past) session, or None when it
        is outside the historical window — the
        pallet_session::historical membership proof for offence
        reports."""
        if session == self.session_index:
            return list(self.staking.validators)
        return self.historical_validators.get(session)

    # ------------------------------------------------------------ hooks

    def add_observer(self, fn) -> None:
        """fn(session_index, ending_validator_set) at each rotation."""
        self._observers.append(fn)

    def validator_set_digest(self) -> str:
        h = hashlib.blake2b(digest_size=16)
        for v in sorted(self.staking.validators):
            h.update(v.encode() + b"\x00" + self.keys.get(v, b""))
        return h.hexdigest()

    def record_genesis_set(self) -> None:
        """Pin session 0's authority set (the runtime calls this after
        seating the genesis validators) so evidence against a genesis
        authority verifies before the first rotation."""
        self.historical[0] = self.validator_set_digest()
        self.historical_validators[0] = list(self.staking.validators)

    def on_initialize(self, now: int) -> None:
        if now % self.session_length != 0:
            return
        ending = list(self.staking.validators)
        for fn in self._observers:
            fn(self.session_index, ending)
        self.session_index += 1
        # era boundary every sessions_per_era sessions: convictions
        # apply FIRST (deferred offences land in this exact block on
        # every replica), then the era closes, then the election runs
        # with the chills already visible.
        if self.session_index % self.sessions_per_era == 0:
            if self.offences is not None:
                self.offences.apply_pending()
            self.staking.end_era()
            if self.staking.candidates:
                self.rrsc.rotate_epoch()
        self.historical[self.session_index] = self.validator_set_digest()
        self.historical_validators[self.session_index] = list(
            self.staking.validators
        )
        horizon = self.session_index - HISTORY_DEPTH_SESSIONS
        if horizon >= 0:
            self.historical.pop(horizon, None)
            self.historical_validators.pop(horizon, None)
        self.state.deposit_event(
            MOD, "NewSession", index=self.session_index
        )
