"""Session pallet: keyed authority sessions driving era rotation.

Role match: stock `pallet_session` + `pallet_session::historical` as the
reference wires them (runtime/src/lib.rs:1484-1527, session keys feeding
the RRSC/GRANDPA/im-online authority sets; SessionsPerEra = 6 with 1 h
epochs, runtime/src/lib.rs:245).  Collapsed onto this framework's
deterministic runtime:

 * accounts register session keys (`set_keys`/`purge_keys` — the opaque
   SessionKeys blob role; here a single BLS public key per authority);
 * the session index advances every `session_length` blocks; every
   `sessions_per_era`-th rotation ends the staking era and runs the
   credit-weighted RRSC election (chain/rrsc.py);
 * each rotation records the validator-set digest in `historical` (the
   pallet_session::historical root used for offence proofs) and
   notifies registered observers (im-online's liveness sweep).
"""

from __future__ import annotations

import hashlib

from .state import ChainState
from .types import AccountId, ensure

MOD = "session"


class SessionPallet:
    def __init__(
        self,
        state: ChainState,
        staking,
        rrsc,
        session_length: int,
        sessions_per_era: int = 6,
    ) -> None:
        self.state = state
        self.staking = staking
        self.rrsc = rrsc
        self.session_length = max(1, session_length)
        self.sessions_per_era = sessions_per_era
        self.session_index: int = 0
        self.keys: dict[AccountId, bytes] = {}
        # session index -> hex digest of the active validator set (the
        # historical-root role for offence proofs)
        self.historical: dict[int, str] = {}
        self._observers: list = []  # on_new_session(index, validators)

    # ------------------------------------------------------------ keys

    def set_keys(self, sender: AccountId, keys: bytes) -> None:
        """Register an authority's session keys (stock set_keys; the
        reference requires a bonded controller — same gate here)."""
        ensure(len(keys) > 0, MOD, "EmptyKeys")
        ensure(
            sender in self.staking.ledger or sender in self.staking.bonded.values(),
            MOD, "NoAssociatedValidatorId",
        )
        self.keys[sender] = bytes(keys)
        self.state.deposit_event(MOD, "KeysSet", who=sender)

    def purge_keys(self, sender: AccountId) -> None:
        ensure(sender in self.keys, MOD, "NoKeys")
        del self.keys[sender]
        self.state.deposit_event(MOD, "KeysPurged", who=sender)

    # ------------------------------------------------------------ hooks

    def add_observer(self, fn) -> None:
        """fn(session_index, ending_validator_set) at each rotation."""
        self._observers.append(fn)

    def validator_set_digest(self) -> str:
        h = hashlib.blake2b(digest_size=16)
        for v in sorted(self.staking.validators):
            h.update(v.encode() + b"\x00" + self.keys.get(v, b""))
        return h.hexdigest()

    def on_initialize(self, now: int) -> None:
        if now % self.session_length != 0:
            return
        ending = list(self.staking.validators)
        for fn in self._observers:
            fn(self.session_index, ending)
        self.session_index += 1
        # era boundary every sessions_per_era sessions
        if self.session_index % self.sessions_per_era == 0:
            self.staking.end_era()
            if self.staking.candidates:
                self.rrsc.rotate_epoch()
        self.historical[self.session_index] = self.validator_set_digest()
        self.state.deposit_event(
            MOD, "NewSession", index=self.session_index
        )
