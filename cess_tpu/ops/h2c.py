"""Batched SSWU hash-to-curve on TPU — the verifier's random oracle.

The PoDR2 combined check needs H(name ‖ index) for every (proof,
challenged chunk) pair: at north-star scale that is millions of
hash-to-curve evaluations, the single largest cost in the whole
pipeline (capability match: hash_to_point inside the reference's
verify, utils/verify-bls-signatures/src/lib.rs:23-31, invoked per
signature check).  This module runs the expensive half on device:

  host (native/blsmap.cpp):  expand_message_xmd + hash_to_field —
      SHA-256 work, ~1 µs/pair with SHA-NI — emitting, per message,
      two canonical field elements u0, u1 plus two predicate bits each
      (sgn0(u), sswu-exceptional(u)) that the device kernel would
      otherwise need canonical passes to derive.
  device (this module):      the two simplified-SWU maps onto the
      11-isogenous curve E' (one (p-3)/4 exponentiation each — the
      dominant ~480 field muls), the complete E' addition, and the
      11-isogeny back to E, all over the base-4096 limb field kernels
      of ops/g1.py.

COFACTOR IS NOT CLEARED HERE.  The output points live on E(Fp), not
necessarily in the r-order subgroup.  Callers fold the effective
cofactor into their scalars instead: for any point P on E and scalar s,
[s]([h_eff]P) = [s·h_eff]P, so an MSM over uncleared points with
scalars s·h_eff (as raw integers — ops/g1.py ladders never reduce mod
r) equals the MSM over cleared points with scalars s.  This removes a
64-bit double-and-add (~550 muls) per point and moves it into scalar
width (+64 bits on one MSM), which amortises across the batch.

RFC 9380 straight-line SSWU (Appendix F.2) is used rather than the
host's branchy form (ops/bls12_381.map_to_curve_g1) — the two are the
same function; bit-identity of the group-level result is asserted in
tests/test_h2c.py.

The predicates the straight-line form needs mid-flight (is-square,
sgn0) require CANONICAL values, which the loose limb representation
does not carry.  `_canon_mod_p` produces exact base-4096 digits of
x mod p from loose limbs via two parallel-prefix tricks (Kogge–Stone
carry resolution, then 14 binary compare-subtract rounds against
k·p) — ~1 mul-equivalent of vector work, used only for the predicate
bits.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from . import _sswu_g1
from .bls12_381 import H_EFF_G1, P
from .g1 import (
    BASE,
    L,
    LIMB_BITS,
    NP_LIMBS,
    _prefix_or_and,
    _select,
    addm,
    be48_to_limb_rows,
    fp_to_limbs,
    mulm,
    smallmul,
    subm,
)

H_EFF = H_EFF_G1

# ------------------------------------------------------------- constants

# Inside the fused Pallas map kernel, Fp constants (SSWU parameters, the
# isogeny coefficient rows, the k·p reduction table) must arrive as
# kernel INPUTS — Pallas rejects captured array constants (same
# constraint as ops/g1.py's fold tables).  The kernel packs them into
# one (n_consts, 33) array, installs it in g1's _TABLE_OVERRIDE context
# under "fpconsts", and _const() resolves by value → row index.
_CONST_VALUES: list[int] = []
_CONST_INDEX: dict[int, int] = {}


def _register_const(x: int) -> int:
    x %= P
    if x not in _CONST_INDEX:
        _CONST_INDEX[x] = len(_CONST_VALUES)
        _CONST_VALUES.append(x)
    return _CONST_INDEX[x]


def _const(x: int, ndim: int) -> jnp.ndarray:
    """Full-width Fp constant broadcast over an ndim-batch limb array.
    Inside the Pallas trace the value is SLICED from a pre-shaped input
    table (fpconsts2: (33, n), fpconsts3: (33, 1, n)) — Mosaic does not
    lower rank-expanding reshapes, so no reshape happens in-kernel."""
    from .g1 import _TABLE_OVERRIDE

    row = _register_const(x)
    ov = _TABLE_OVERRIDE.get()
    if ov is not None and "fpconsts2" in ov:
        if ndim != 2:
            raise ValueError("fpconsts: Pallas map kernel is rank-2 only")
        return ov["fpconsts2"][:, row : row + 1]
    return jnp.asarray(fp_to_limbs(x % P)).reshape((L,) + (1,) * (ndim - 1))


@lru_cache(maxsize=None)
def _const_table(n_consts: int) -> np.ndarray:
    """(n_consts, 33) limb rows of the registered Fp constants, in
    registration order.  Keyed by registry size so a stale cache can
    never be served; _ensure_const_registry() pre-registers everything
    the map kernel uses before the table is packed."""
    out = np.zeros((n_consts, L), dtype=np.int32)
    for i, v in enumerate(_CONST_VALUES[:n_consts]):
        out[i] = fp_to_limbs(v)
    return out


def _fp_sqrt_exact(x: int) -> int:
    """Host sqrt for constant derivation (p ≡ 3 mod 4)."""
    r = pow(x % P, (P + 1) // 4, P)
    if r * r % P != x % P:
        raise ValueError("constant is not a quadratic residue")
    return r


A_PRIME = _sswu_g1.A_PRIME
B_PRIME = _sswu_g1.B_PRIME
Z_SSWU = _sswu_g1.Z_SSWU  # 11 — small enough for smallmul
B3_PRIME = 3 * B_PRIME % P
# c2 = sqrt(−Z) (exists: χ(−Z) = χ(−1)·χ(Z) = (−1)(−1) for p ≡ 3 mod 4
# and non-square Z).  Needed so the non-square branch's final
# y = Zu³·c2·y1 squares to gx2 = Z³u⁶·gx1 given y1² = −(u/v); either
# root works — the sgn0 correction fixes the sign.
C2 = _fp_sqrt_exact(-Z_SSWU % P)

# 4-bit MSB-first digits of c1 = (p-3)/4 for the fixed-window chain.
_C1 = (P - 3) // 4
_C1_DIGITS = tuple(
    (_C1 >> (4 * k)) & 0xF for k in range((_C1.bit_length() + 3) // 4)
)[::-1]


@lru_cache(maxsize=None)
def _kp_digits() -> np.ndarray:
    """(14, 33) exact base-4096 digits of k·p for k = 2^13 … 2^0."""
    out = np.zeros((14, L), dtype=np.int32)
    for row, sh in enumerate(range(13, -1, -1)):
        out[row] = fp_to_limbs((1 << sh) * P)
    return out


# ------------------------------------------------- canonical predicates




def _limb_scalar(val, like: jnp.ndarray) -> jnp.ndarray:
    """Limb array with limb 0 = val, rest 0, shaped like `like` — via an
    iota mask (Pallas-safe: no scatter / .at updates inside kernels)."""
    limb0 = jax.lax.broadcasted_iota(jnp.int32, like.shape, 0) == 0
    return jnp.where(limb0, val, 0)


def _canon_mod_p_seq(x: jnp.ndarray, kp: jnp.ndarray) -> jnp.ndarray:
    """Pallas-safe _canon_mod_p: sequential carry/borrow chains unrolled
    over the 33 limbs instead of associative_scan (which does not lower
    inside a Mosaic kernel).  kp: (14, 33) digits of 2^k·p, from the
    kernel's input tables."""
    rows = [x[i : i + 1] for i in range(L)]  # keep-rank slices
    carry = jnp.zeros_like(rows[0])
    f = []
    for i in range(L):
        t = rows[i] + carry
        f.append(t & (BASE - 1))
        carry = t >> LIMB_BITS
    for row in range(14):
        borrow = jnp.zeros_like(f[0])
        s = []
        for i in range(L):
            t = f[i] - kp[row, i] - borrow
            neg = (t < 0).astype(jnp.int32)
            s.append(t + neg * BASE)
            borrow = neg
        keep = borrow == 0  # D ≥ k·p: take the difference
        f = [jnp.where(keep, s[i], f[i]) for i in range(L)]
    return jnp.concatenate(f, axis=0)


def _canon_mod_p(x: jnp.ndarray) -> jnp.ndarray:
    """Loose (33, …) limbs → EXACT canonical base-4096 digits of x mod p.

    Stage 1 (carry resolution): limbs are in [0, 4096]; split into digit
    + carry bit and resolve the (worst-case cascading) carries with one
    Kogge–Stone propagate/generate scan.
    Stage 2 (reduction): the value is < 2^384 + 8192·p (the loose
    bound), so ⌊x/p⌋ ≤ 2^13+9; 14 binary compare-subtract rounds against
    2^k·p (borrow resolution by the same scan, keep the difference when
    it is non-negative) leave the canonical residue.

    Inside a Pallas trace (g1._TABLE_OVERRIDE provides "kp") the
    sequential unrolled variant runs instead — same digits exactly."""
    from .g1 import _TABLE_OVERRIDE

    ov = _TABLE_OVERRIDE.get()
    if ov is not None and "kp" in ov:
        return _canon_mod_p_seq(x, ov["kp"])
    e = x & (BASE - 1)
    c = x >> LIMB_BITS  # ∈ {0, 1} for loose inputs
    tail = [(0, 0)] * (x.ndim - 1)
    a = e + jnp.pad(c[:-1], [(1, 0)] + tail)  # ≤ 4096
    g = (a >= BASE).astype(jnp.int32)
    pr = (a == BASE - 1).astype(jnp.int32)
    cin = jnp.pad(_prefix_or_and(g, pr)[:-1], [(1, 0)] + tail)
    f = (a + cin) & (BASE - 1)

    kp = _kp_digits()
    for row in range(14):
        t = f - kp[row].reshape((L,) + (1,) * (x.ndim - 1))
        gb = (t < 0).astype(jnp.int32)
        pb = (t == 0).astype(jnp.int32)
        scan = _prefix_or_and(gb, pb)
        bin_ = jnp.pad(scan[:-1], [(1, 0)] + tail)
        borrow_out = scan[-1]
        s = (t - bin_) & (BASE - 1)
        f = jnp.where((borrow_out == 0)[None], s, f)
    return f


def _parity_mod_p(x: jnp.ndarray) -> jnp.ndarray:
    """sgn0 of a loose value: parity of the canonical residue, (…) int32."""
    return _canon_mod_p(x)[0] & 1


def _is_zero_mod_p(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(_canon_mod_p(x) == 0, axis=0)


def _eq_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _is_zero_mod_p(subm(a, b))


# ------------------------------------------------------------- SSWU map


def _pow_c1_xla(t: jnp.ndarray) -> jnp.ndarray:
    pre = [jnp.zeros_like(t).at[0].set(1), t]
    for _ in range(14):
        pre.append(mulm(pre[-1], t))
    table = jnp.stack(pre)  # (16, 33, …)
    digits = jnp.asarray(np.asarray(_C1_DIGITS, dtype=np.int32))

    def body(i, acc):
        for _ in range(4):
            acc = mulm(acc, acc)
        m = jax.lax.dynamic_index_in_dim(table, digits[i], 0, keepdims=False)
        return mulm(acc, m)

    acc = table[_C1_DIGITS[0]]
    return jax.lax.fori_loop(1, len(_C1_DIGITS), body, acc)


def _powc1_tile_kernel(digits_ref, t_ref, t35_ref, t3_ref, t2_ref,
                       pad_ref, o_ref, pre_ref, *, n_digits: int):
    """One VMEM-resident tile of the fixed-window chain: the ~480-mul
    bit loop runs on-chip (the per-op XLA path round-trips every
    intermediate through HBM and is bandwidth-bound, as with ops/g1.py's
    ladder).  The window table lives in a VMEM scratch ref because
    in-loop dynamic indexing is only lowerable on refs (pl.ds), not
    values."""
    from jax.experimental import pallas as pl

    from .g1 import _FOLD_HIGHS, _TABLE_OVERRIDE

    token = _TABLE_OVERRIDE.set(
        {
            "pow": {
                h: ref[:]
                for h, ref in zip(_FOLD_HIGHS, (t35_ref, t3_ref, t2_ref))
            },
            "subpad": pad_ref[:],
        }
    )
    try:
        t = t_ref[:]
        limb0 = jax.lax.broadcasted_iota(jnp.int32, t.shape, 0) == 0
        pre_ref[0] = jnp.where(limb0, 1, 0)
        pre_ref[1] = t
        cur = t
        for k in range(2, 16):
            cur = mulm(cur, t)
            pre_ref[k] = cur

        def body(i, acc):
            for _ in range(4):
                acc = mulm(acc, acc)
            d = digits_ref[pl.ds(i, 1), :][0, 0]
            m = pre_ref[pl.ds(d, 1)][0]
            return mulm(acc, m)

        acc = pre_ref[_C1_DIGITS[0]]
        acc = jax.lax.fori_loop(1, n_digits, body, acc)
    finally:
        _TABLE_OVERRIDE.reset(token)
    o_ref[:] = acc


_POW_TILE = 512


def _pow_c1_pallas(t: jnp.ndarray) -> jnp.ndarray:
    """Pallas chain over (33, N) lanes (N a power of two ≥ tile)."""
    from functools import partial as _partial

    from jax.experimental import pallas as pl

    from .g1 import _FOLD_HIGHS, _pow_table, _sub_pad

    n = t.shape[1]
    tile = min(_POW_TILE, n)
    spec = pl.BlockSpec((L, tile), lambda i: (0, i))
    t35, t3, t2 = (
        jnp.asarray(_pow_table(NP_LIMBS, h)) for h in _FOLD_HIGHS
    )
    padv = jnp.asarray(np.asarray(_sub_pad())).reshape(L, 1)
    digits = jnp.asarray(
        np.asarray(_C1_DIGITS, dtype=np.int32).reshape(-1, 1)
    )
    full = lambda a: pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)  # noqa: E731

    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _partial(_powc1_tile_kernel, n_digits=len(_C1_DIGITS)),
        grid=(n // tile,),
        in_specs=[
            full(digits), spec, full(t35), full(t3), full(t2), full(padv),
        ],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((L, n), jnp.int32),
        scratch_shapes=[pltpu.VMEM((16, L, tile), jnp.int32)],
    )(digits, t, t35, t3, t2, padv)


# In-kernel pow hook: the fused map kernel installs a closure over its
# VMEM scratch here so _sqrt_ratio's chain call stays in the same trace.
import contextvars

_POW_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "h2c_pow_override", default=None
)


def _pow_c1(t: jnp.ndarray) -> jnp.ndarray:
    """t^((p-3)/4): 4-bit fixed-window chain (14 precomp + 94×(4 sq + 1
    mul) ≈ 484 muls), the dominant cost of each SSWU map.  Inside the
    fused map kernel the scratch-backed Pallas variant runs; standalone
    TPU callers get the tiled Pallas kernel; elsewhere per-op XLA."""
    hook = _POW_OVERRIDE.get()
    if hook is not None:
        return hook(t)
    if jax.default_backend() != "tpu":
        return _pow_c1_xla(t)
    shape = t.shape
    flat = t.reshape(L, -1)
    if flat.shape[1] % _POW_TILE and (
        flat.shape[1] & (flat.shape[1] - 1)
    ) != 0:
        return _pow_c1_xla(t)  # non-power-of-two lanes: keep it simple
    return _pow_c1_pallas(flat).reshape(shape)


def _sqrt_ratio(u: jnp.ndarray, v: jnp.ndarray):
    """RFC 9380 F.2.1.2 sqrt_ratio_3mod4 → (isQR (…) bool, y (33, …))."""
    tv1 = mulm(v, v)
    tv2 = mulm(u, v)
    tv1 = mulm(tv1, tv2)  # u·v³
    y1 = mulm(_pow_c1(tv1), tv2)
    y2 = mulm(y1, _const(C2, y1.ndim))
    tv3 = mulm(mulm(y1, y1), v)
    is_qr = _eq_mod_p(tv3, u)
    return is_qr, _select(is_qr, y1, y2)


def _sswu_map(u: jnp.ndarray, sgn_u: jnp.ndarray, exc: jnp.ndarray):
    """Straight-line simplified SWU onto E' (RFC 9380 F.2).

    u: (33, …) loose limbs; sgn_u/exc: (…) int32 predicate inputs
    (sgn0(u) and [Z²u⁴ + Zu² ≡ 0], host-derived).  Returns the mapped
    point as a projective triple (xn : y·xd : xd) on E'."""
    ndim = u.ndim
    zero = jnp.zeros_like(u)
    one = _limb_scalar(1, u)
    a_c = _const(A_PRIME, ndim)
    b_c = _const(B_PRIME, ndim)

    tv1 = smallmul(mulm(u, u), Z_SSWU)  # Z·u²
    tv2 = addm(mulm(tv1, tv1), tv1)  # Z²u⁴ + Zu²
    tv3 = mulm(addm(tv2, one), b_c)  # B(tv2 + 1)
    z_c = _limb_scalar(Z_SSWU, u)
    tv4 = _select(exc == 1, z_c, subm(zero, tv2))  # CMOV(Z, −tv2, tv2≠0)
    tv4 = mulm(tv4, a_c)
    t2 = mulm(tv3, tv3)
    tv6 = mulm(tv4, tv4)
    tv5 = mulm(tv6, a_c)
    t2 = mulm(addm(t2, tv5), tv3)
    tv6 = mulm(tv6, tv4)  # tv4³-bearing denominator
    tv5 = mulm(tv6, b_c)
    t2 = addm(t2, tv5)  # g(x1)·tv4³ numerator
    x = mulm(tv1, tv3)
    is_qr, y1 = _sqrt_ratio(t2, tv6)
    y = mulm(mulm(tv1, u), y1)
    x = _select(is_qr, tv3, x)
    y = _select(is_qr, y1, y)
    e1 = sgn_u == _parity_mod_p(y)
    y = _select(e1, y, subm(zero, y))
    # affine x = x/tv4, y  →  projective (x : y·tv4 : tv4)
    return x, mulm(y, tv4), tv4


# --------------------------------------------------- E' complete addition


def _pt_add_aprime(p, q):
    """Complete projective addition on E' (a = A' ≠ 0): Renes–Costello–
    Batina 2016 Algorithm 1 — exception-free for every input pair on the
    odd-order-free E' as well (completeness needs only short-Weierstrass
    + prime field)."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    ndim = X1.ndim
    a_c = _const(A_PRIME, ndim)
    b3_c = _const(B3_PRIME, ndim)
    t0 = mulm(X1, X2)
    t1 = mulm(Y1, Y2)
    t2 = mulm(Z1, Z2)
    t3 = mulm(addm(X1, Y1), addm(X2, Y2))
    t3 = subm(t3, addm(t0, t1))  # X1Y2 + X2Y1
    t4 = mulm(addm(X1, Z1), addm(X2, Z2))
    t4 = subm(t4, addm(t0, t2))  # X1Z2 + X2Z1
    t5 = mulm(addm(Y1, Z1), addm(Y2, Z2))
    t5 = subm(t5, addm(t1, t2))  # Y1Z2 + Y2Z1
    Z3 = mulm(t4, a_c)
    X3 = mulm(t2, b3_c)
    Z3 = addm(X3, Z3)  # aT4 + 3bT2
    X3 = subm(t1, Z3)
    Z3 = addm(t1, Z3)
    Y3 = mulm(X3, Z3)
    t1 = addm(addm(t0, t0), t0)  # 3X1X2
    t2 = mulm(t2, a_c)
    t4 = mulm(t4, b3_c)
    t1 = addm(t1, t2)  # 3X1X2 + aZ1Z2
    t2 = subm(t0, t2)  # X1X2 − aZ1Z2
    t2 = mulm(t2, a_c)
    t4 = addm(t4, t2)  # 3bT4 + a(X1X2 − aZ1Z2)
    t0 = mulm(t1, t4)
    Y3 = addm(Y3, t0)
    t0 = mulm(t5, t4)
    X3 = mulm(t3, X3)
    X3 = subm(X3, t0)
    t0 = mulm(t3, t1)
    Z3 = mulm(t5, Z3)
    Z3 = addm(Z3, t0)
    return X3, Y3, Z3


# ------------------------------------------------------------- isogeny


def _iso_eval(X, Y, Z):
    """11-isogeny E' → E on a projective batch: homogenised Horner over
    the derived coefficient tables (ops/_sswu_g1.py).  x' = XN/(Z·XD),
    y' = (Y/Z)·YN/YD with YN, YD homogenised to the common degree 15.
    Output is projective on E; Z-of-zero (isogeny kernel, or an input
    at infinity) canonicalises to (0 : 1 : 0)."""
    ndim = X.ndim
    max_deg = 15
    zpow = [None, Z]
    for _ in range(max_deg - 1):
        zpow.append(mulm(zpow[-1], Z))

    def horner(coeffs):
        # First step folded in (acc = k_deg·X + k_{deg-1}·Z) so the
        # accumulator always originates from a materialised mulm —
        # Mosaic crashes slicing rows of a lazily-broadcast (33, 1)
        # constant inside _polymul.
        deg = len(coeffs) - 1
        acc = addm(
            mulm(X, _const(coeffs[deg], ndim)),
            mulm(zpow[1], _const(coeffs[deg - 1], ndim)),
        )
        for i in range(deg - 2, -1, -1):
            acc = addm(
                mulm(acc, X), mulm(zpow[deg - i], _const(coeffs[i], ndim))
            )
        return acc

    xn = horner(_sswu_g1.X_NUM)
    xd = horner(_sswu_g1.X_DEN)
    yn = horner(_sswu_g1.Y_NUM)
    yd = horner(_sswu_g1.Y_DEN)
    XE = mulm(xn, yd)
    YE = mulm(mulm(Y, yn), xd)
    ZE = mulm(mulm(Z, xd), yd)
    inf = _is_zero_mod_p(ZE)
    zero = jnp.zeros_like(XE)
    one = _limb_scalar(1, XE)
    return (
        _select(inf, zero, XE),
        _select(inf, one, YE),
        _select(inf, zero, ZE),
    )


# ------------------------------------------------------------- kernels


def _map_pairs_core(u, sgn, exc):
    x, y, z = _sswu_map(u, sgn, exc)
    p0 = (x[:, 0], y[:, 0], z[:, 0])
    p1 = (x[:, 1], y[:, 1], z[:, 1])
    Xs, Ys, Zs = _pt_add_aprime(p0, p1)
    return _iso_eval(Xs, Ys, Zs)


@jax.jit
def _map_pairs_xla(u, sgn, exc):
    return _map_pairs_core(u, sgn, exc)


def _ensure_const_registry() -> int:
    for v in (A_PRIME, B_PRIME, B3_PRIME, C2):
        _register_const(v)
    for lst in (
        _sswu_g1.X_NUM, _sswu_g1.X_DEN, _sswu_g1.Y_NUM, _sswu_g1.Y_DEN
    ):
        for c in lst:
            _register_const(c)
    return len(_CONST_VALUES)


def _map_tile_kernel(digits_ref, u_ref, sgn_ref, exc_ref, t35_ref, t3_ref,
                     t2_ref, pad_ref, kp_ref, fc2_ref, oX_ref,
                     oY_ref, oZ_ref, pre_ref, *, n_digits: int):
    """The WHOLE pair map fused in one VMEM-resident tile: two SSWU maps
    (scratch-backed pow chains), E' complete add, 11-isogeny, canonical
    predicate passes — ~1100 field muls per point with no HBM
    round-trips between them.  Constants/tables arrive as inputs and
    are installed via the g1/_POW_OVERRIDE contexts for the trace.

    Everything is RANK 2 — (33, lanes) — because Mosaic does not lower
    rank-expanding reshapes: a tile of T points arrives as 2T lanes,
    u0s in the first half, u1s in the second (host pre-interleave in
    _map_pairs_kernel)."""
    from jax.experimental import pallas as pl

    from .g1 import _FOLD_HIGHS, _TABLE_OVERRIDE

    def pow_hook(t):
        limb0 = jax.lax.broadcasted_iota(jnp.int32, t.shape, 0) == 0
        pre_ref[0] = jnp.where(limb0, 1, 0)
        pre_ref[1] = t
        cur = t
        for k in range(2, 16):
            cur = mulm(cur, t)
            pre_ref[k] = cur

        def body(i, acc):
            for _ in range(4):
                acc = mulm(acc, acc)
            d = digits_ref[pl.ds(i, 1), :][0, 0]
            m = pre_ref[pl.ds(d, 1)][0]
            return mulm(acc, m)

        acc = pre_ref[_C1_DIGITS[0]]
        return jax.lax.fori_loop(1, n_digits, body, acc)

    token = _TABLE_OVERRIDE.set(
        {
            "pow": {
                h: ref[:]
                for h, ref in zip(_FOLD_HIGHS, (t35_ref, t3_ref, t2_ref))
            },
            "subpad": pad_ref[:],
            "kp": kp_ref[:],
            "fpconsts2": fc2_ref[:],
        }
    )
    tok2 = _POW_OVERRIDE.set(pow_hook)
    try:
        u = u_ref[:]  # (33, 2T)
        sgn = sgn_ref[:][0]  # (1, 2T) → (2T,)
        exc = exc_ref[:][0]
        x, y, z = _sswu_map(u, sgn, exc)
        half = u.shape[1] // 2
        p0 = (x[:, :half], y[:, :half], z[:, :half])
        p1 = (x[:, half:], y[:, half:], z[:, half:])
        Xs, Ys, Zs = _pt_add_aprime(p0, p1)
        XE, YE, ZE = _iso_eval(Xs, Ys, Zs)
    finally:
        _POW_OVERRIDE.reset(tok2)
        _TABLE_OVERRIDE.reset(token)
    oX_ref[:] = XE
    oY_ref[:] = YE
    oZ_ref[:] = ZE


_MAP_TILE = 1024


def _map_pairs_pallas(u, sgn, exc):
    """u: (33, 2, N); per tile of T points the lane axis is laid out as
    [u0 of the tile's points | u1 of the tile's points] so the kernel
    can split pairs with pure slices."""
    from functools import partial as _partial

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .g1 import _FOLD_HIGHS, _pow_table, _sub_pad

    n = u.shape[2]
    tile = min(_MAP_TILE, n)
    n_tiles = n // tile
    # (33, 2, n_tiles, T) → (33, n_tiles, 2, T) → (33, 2N) tile-interleaved
    u2 = jnp.reshape(
        jnp.transpose(u.reshape(L, 2, n_tiles, tile), (0, 2, 1, 3)),
        (L, 2 * n),
    )
    flat = lambda f: jnp.reshape(  # noqa: E731
        jnp.transpose(f.reshape(2, n_tiles, tile), (1, 0, 2)), (1, 2 * n)
    )
    sgn2 = flat(sgn)
    exc2 = flat(exc)

    spec_u = pl.BlockSpec((L, 2 * tile), lambda i: (0, i))
    spec_f = pl.BlockSpec((1, 2 * tile), lambda i: (0, i))
    spec_o = pl.BlockSpec((L, tile), lambda i: (0, i))
    t35, t3, t2 = (
        jnp.asarray(_pow_table(NP_LIMBS, h)) for h in _FOLD_HIGHS
    )
    padv = jnp.asarray(np.asarray(_sub_pad())).reshape(L, 1)
    digits = jnp.asarray(
        np.asarray(_C1_DIGITS, dtype=np.int32).reshape(-1, 1)
    )
    kp = jnp.asarray(_kp_digits())
    n_consts = _ensure_const_registry()
    fc2 = jnp.asarray(_const_table(n_consts).T)  # (33, n_consts)
    full = lambda a: pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)  # noqa: E731

    shape = jax.ShapeDtypeStruct((L, n), jnp.int32)
    return pl.pallas_call(
        _partial(_map_tile_kernel, n_digits=len(_C1_DIGITS)),
        grid=(n_tiles,),
        in_specs=[
            full(digits), spec_u, spec_f, spec_f,
            full(t35), full(t3), full(t2), full(padv), full(kp),
            full(fc2),
        ],
        out_specs=[spec_o, spec_o, spec_o],
        out_shape=[shape, shape, shape],
        scratch_shapes=[pltpu.VMEM((16, L, 2 * tile), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(digits, u2, sgn2, exc2, t35, t3, t2, padv, kp, fc2)


def _map_pairs_kernel(u, sgn, exc):
    """u: (33, 2, N) loose limbs (u0 row 0, u1 row 1); sgn/exc: (2, N)
    int32.  Returns the UNCLEARED hash point batch (X, Y, Z) (33, N) on
    E — map both elements, add on E', apply the isogeny once (the
    isogeny is a group homomorphism, so iso(m0 +' m1) = iso(m0) +
    iso(m1), matching the host's per-point route).  Fully fused Pallas
    kernel on TPU; per-op XLA elsewhere — bit-identical either way."""
    if jax.default_backend() == "tpu" and u.shape[2] % _MAP_TILE == 0:
        return _map_pairs_pallas_jit(u, sgn, exc)
    return _map_pairs_xla(u, sgn, exc)


# Module-level jit (the glv.py _glv_fold_pallas_jit idiom): building
# the wrapper inside _map_pairs_kernel re-traced the Pallas kernel on
# every eager call (hash_pairs_device) — caught by cesslint jit-in-body.
_map_pairs_pallas_jit = jax.jit(_map_pairs_pallas)


# ------------------------------------------------------------- host API


def u_bytes_to_limbs(u_be: np.ndarray) -> np.ndarray:
    """(…, 48) big-endian canonical bytes → (33, …) int32 limbs,
    vectorised — the limb-major view of g1.be48_to_limb_rows (one
    shared byte-twiddle implementation)."""
    return np.moveaxis(be48_to_limb_rows(u_be), -1, 0)


def _u_host_fallback(names, name_ids, indices, dst):
    """Pure-Python XMD path (no native library): correct, slow."""
    from . import bls12_381 as bls

    n = len(name_ids)
    u = np.zeros((n, 2, 48), dtype=np.uint8)
    flags = np.zeros(n, dtype=np.uint8)
    neg_inv_z = -pow(Z_SSWU, P - 2, P) % P
    for row, (k, idx) in enumerate(zip(name_ids, indices)):
        msg = names[int(k)] + b"/" + int(idx).to_bytes(8, "little")
        u0, u1 = bls.hash_to_field_fp(msg, dst, 2)
        f = 0
        for e, uu in enumerate((u0, u1)):
            u[row, e] = np.frombuffer(uu.to_bytes(48, "big"), dtype=np.uint8)
            if uu & 1:
                f |= 1 << (2 * e)
            if uu == 0 or uu * uu % P == neg_inv_z:
                f |= 1 << (2 * e + 1)
        flags[row] = f
    return u, flags


def u_for_pairs(names: list[bytes], name_ids, indices, dst: bytes,
                threads: int = 8):
    """Host front half: (u_limbs (33, 2, N), sgn (2, N), exc (2, N))
    numpy arrays for the device map kernel, via the native XMD batch
    when built (threaded — harmless on single-core hosts)."""
    name_ids = np.ascontiguousarray(name_ids, dtype=np.uint32)
    indices = np.ascontiguousarray(indices, dtype=np.uint64)
    try:
        from .. import native

        u, flags = native.xmd_u_indexed(
            names, name_ids, indices, dst, threads=threads
        )
    except (AssertionError, AttributeError, OSError, RuntimeError):
        u, flags = _u_host_fallback(names, name_ids, indices, dst)
    u_limbs = u_bytes_to_limbs(u)  # (33, N, 2)
    u_limbs = np.swapaxes(u_limbs, 1, 2)  # (33, 2, N)
    f = flags.astype(np.int32)
    sgn = np.stack([f & 1, (f >> 2) & 1])  # (2, N)
    exc = np.stack([(f >> 1) & 1, (f >> 3) & 1])
    return u_limbs, sgn, exc


def _pad_pow2_lanes(arrs, n):
    m = 1 << max(0, (n - 1).bit_length())
    if jax.default_backend() == "tpu":
        m = max(m, _MAP_TILE)  # stay on the fused-kernel path
    if m == n:
        return arrs, n
    return [
        np.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, m - n)]) for a in arrs
    ], m


def hash_pairs_device(
    names: list[bytes], name_ids, indices, dst: bytes
):
    """(name, index) pairs → UNCLEARED hash points as device limb arrays
    (X, Y, Z) of shape (33, N) — ready for ops/g1.py MSMs with
    h_eff-folded scalars.  Padding lanes (u = 0) are mapped like any
    other input and must be ignored by the caller (hence the returned
    true count)."""
    n = len(name_ids)
    u_limbs, sgn, exc = u_for_pairs(names, name_ids, indices, dst)
    (u_limbs, sgn, exc), m = _pad_pow2_lanes([u_limbs, sgn, exc], n)
    X, Y, Z = _map_pairs_kernel(
        jnp.asarray(u_limbs), jnp.asarray(sgn), jnp.asarray(exc)
    )
    return (X, Y, Z), n


def hash_pairs_host_points(
    names: list[bytes], name_ids, indices, dst: bytes
):
    """Cleared host G1Points via the device map — bit-identity seam used
    by tests ([h_eff]·device result == ops/bls12_381.hash_to_g1)."""
    from . import g1 as g1mod

    (X, Y, Z), n = hash_pairs_device(names, name_ids, indices, dst)
    pts = g1mod.projective_to_points(
        np.asarray(X).T[:n], np.asarray(Y).T[:n], np.asarray(Z).T[:n]
    )
    return [p._mul_raw(H_EFF) if not p.is_infinity() else p for p in pts]
