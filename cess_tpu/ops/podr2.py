"""PoDR2: proofs of data reduplication & recovery — scheme definition.

The reference chain carries PoDR2 artifacts but computes them off-chain in
TEE/miner tooling that is not in the tree (reference: the Podr2Key on chain
at c-pallets/tee-worker/src/lib.rs:120-121, the σ proof blobs at
c-pallets/audit/src/types.rs:33-41, the 47-index/47-coefficient challenge at
c-pallets/audit/src/lib.rs:906-924, and the declared verification seam at
audit/src/lib.rs:484).  This module defines the framework's scheme —
a Shacham–Waters compact proof of retrievability over BLS12-381, chosen
over the reference's RSA flavour because it batch-verifies as MXU-friendly
Zr matrix products plus a constant number of pairings:

  setup     TEE keypair x ∈ Zr, pk = g2^x  (network Podr2Key)
  generators u_j = hash_to_g1("cess/podr2/u" ‖ j) — global, so the
            verifier's u-side collapses across a batch (see batch_verify)
  tag       fragment `name`, data split into n chunks × s sectors × 31 B;
            σ_i = (H(name ‖ i) · Π_j u_j^{m_ij})^x           (48 B each)
  challenge Q = {(i_c, v_c)}: chunk indices + 20-byte coefficients —
            exactly the audit pallet's random_index_list/random_list
  prove     μ_j = Σ_c v_c·m_{i_c j} mod r;   σ = Π_c σ_{i_c}^{v_c}
  verify    e(σ, g2) == e(Π_c H(name‖i_c)^{v_c} · Π_j u_j^{μ_j}, pk)

Batch verification folds N proofs into ONE equation with random 128-bit
weights ρ_b (Bellare–Garay–Rabin small-exponent test):

  e(Π_b σ_b^{ρ_b}, g2) == e( Π_{b,c} H_b,c^{ρ_b v_c} · Π_j u_j^{Σ_b ρ_b μ_bj}, pk )

The Σ_b ρ_b μ_bj term is an (N×s) matrix-vector product over Zr — the part
ops/fr.py runs on TPU; the σ/H MSMs are the ops/g1.py batch kernels; the
two pairings are O(1) per batch.

This host implementation is the bit-exactness reference for the backends in
cess_tpu.proof.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import lru_cache

from . import bls12_381 as bls
from .bls12_381 import G1Point, G2Point, R

SECTOR_SIZE = 31  # bytes per sector; 31*8 = 248 bits < |r| = 255

# Protocol geometry (reference: primitives/common/src/lib.rs:61-62 — 8 MiB
# fragments of 1024 chunks): chunk = 8 KiB = 265 sectors (last one short).
PROTO_CHUNKS = 1024
PROTO_SECTORS = (8192 + SECTOR_SIZE - 1) // SECTOR_SIZE  # 265

U_DST = b"cess/podr2/u/v1"
H_DST = b"cess/podr2/h/v1"
RHO_DST = b"cess/podr2/rho/v1"


@dataclass(frozen=True)
class Podr2Params:
    """Scheme geometry: n chunks of s sectors per fragment."""

    n: int = PROTO_CHUNKS
    s: int = PROTO_SECTORS

    @property
    def chunk_bytes(self) -> int:
        return self.s * SECTOR_SIZE

    @property
    def fragment_bytes(self) -> int:
        return self.n * self.chunk_bytes


@dataclass
class Podr2Proof:
    sigma: bytes          # 48-byte compressed G1
    mu: list[int]         # s scalars mod r

    def encode(self) -> bytes:
        out = [self.sigma]
        out.extend(m.to_bytes(32, "little") for m in self.mu)
        return b"".join(out)

    @classmethod
    def decode(cls, data: bytes, s: int) -> "Podr2Proof":
        if len(data) != 48 + 32 * s:
            raise ValueError("bad proof length")
        sigma = data[:48]
        mu = [
            int.from_bytes(data[48 + 32 * j : 80 + 32 * j], "little")
            for j in range(s)
        ]
        return cls(sigma, mu)

    def commitment(self) -> bytes:
        """On-chain ≤SigmaMax blob: σ plus a binding digest of μ (the full
        proof travels off-chain to the TEE, as in the reference)."""
        return self.sigma + hashlib.sha256(self.encode()).digest()


def keygen(seed: bytes) -> tuple[int, bytes]:
    """TEE keypair; pk is the network Podr2Key (tee-worker lib.rs:166-168)."""
    sk = bls.keygen(b"podr2" + seed)
    return sk, G2Point.from_bytes(bls.sk_to_pk(sk)).to_bytes()


@lru_cache(maxsize=8)
def u_generators(s: int) -> tuple[G1Point, ...]:
    """Global sector generators (cached; deterministic across processes)."""
    return tuple(
        bls.hash_to_g1(U_DST + j.to_bytes(4, "little"), U_DST) for j in range(s)
    )


@lru_cache(maxsize=1 << 16)
def chunk_point(name: bytes, index: int) -> G1Point:
    """H(name ‖ i) — the per-chunk random-oracle point.  Cached: the
    bisection fallback in the proof backends re-visits identical (name, i)
    pairs across overlapping subsets."""
    return bls.hash_to_g1(name + b"/" + index.to_bytes(8, "little"), H_DST)


def chunk_points_batch(
    pairs: list[tuple[bytes, int]], threads: int = 8
) -> list[G1Point]:
    """Batched H(name ‖ i) through the native hash-to-curve kernel
    (native/blsmap.cpp) when built — bit-identical to chunk_point
    (tests/test_native.py) — with a host fallback.  The verifier's
    random-oracle workhorse: the combined check needs one point per
    (proof, challenged chunk)."""
    try:
        from .. import native

        msgs = [
            name + b"/" + index.to_bytes(8, "little") for name, index in pairs
        ]
        out = []
        for x, y in native.hash_to_g1_batch(msgs, H_DST, threads=threads):
            out.append(
                G1Point.infinity() if x == 0 and y == 0 else G1Point(x, y)
            )
        return out
    except (AssertionError, AttributeError, OSError, RuntimeError):
        # no native library, a stale build without the blsmap symbols, or
        # an over-long message — the host path is always correct
        return [chunk_point(name, index) for name, index in pairs]


def split_sectors(chunk: bytes, s: int) -> list[int]:
    """Chunk bytes → s sector scalars (zero-padded little-endian)."""
    chunk = chunk.ljust(s * SECTOR_SIZE, b"\x00")
    return [
        int.from_bytes(chunk[j * SECTOR_SIZE : (j + 1) * SECTOR_SIZE], "little")
        for j in range(s)
    ]


def fragment_sectors(data: bytes, params: Podr2Params) -> list[list[int]]:
    """Fragment bytes → n×s sector matrix."""
    data = data.ljust(params.fragment_bytes, b"\x00")
    return [
        split_sectors(
            data[i * params.chunk_bytes : (i + 1) * params.chunk_bytes], params.s
        )
        for i in range(params.n)
    ]


# ---------------------------------------------------------------- tagging


def tag_chunk(sk: int, name: bytes, index: int, sectors: list[int]) -> bytes:
    """σ_i = (H(name‖i) · Π_j u_j^{m_ij})^x, 48-byte compressed."""
    us = u_generators(len(sectors))
    acc = chunk_point(name, index)
    for u, m in zip(us, sectors):
        if m:
            acc = acc + u.mul(m)
    return acc.mul(sk).to_bytes()


def tag_fragment(sk: int, name: bytes, data: bytes, params: Podr2Params) -> list[bytes]:
    """All n chunk tags for a fragment (the TEE's tag-calculation duty,
    rate-assumed 64 MiB/block in the reference:
    c-pallets/file-bank/src/constants.rs:4)."""
    matrix = fragment_sectors(data, params)
    return [tag_chunk(sk, name, i, row) for i, row in enumerate(matrix)]


# ---------------------------------------------------------------- challenge


@dataclass(frozen=True)
class Challenge:
    """The audit round's (index, coefficient) pairs (reference:
    audit/src/lib.rs:906-924 — 47 of 1024 chunks, 20-byte randoms)."""

    indices: tuple[int, ...]
    randoms: tuple[bytes, ...]  # 20-byte each

    def coefficients(self) -> list[int]:
        return [int.from_bytes(v, "little") for v in self.randoms]

    @classmethod
    def from_net_snapshot(cls, snap) -> "Challenge":
        return cls(tuple(snap.random_index_list), tuple(snap.random_list))


# ---------------------------------------------------------------- prove


def prove(
    tags: list[bytes],
    data: bytes,
    challenge: Challenge,
    params: Podr2Params,
) -> Podr2Proof:
    """Miner-side response: μ vector + aggregated σ."""
    matrix = fragment_sectors(data, params)
    vs = challenge.coefficients()
    mu = [0] * params.s
    for v, i in zip(vs, challenge.indices):
        row = matrix[i]
        for j in range(params.s):
            mu[j] = (mu[j] + v * row[j]) % R
    sigma = G1Point.infinity()
    for v, i in zip(vs, challenge.indices):
        sigma = sigma + G1Point.from_bytes(tags[i]).mul(v)
    return Podr2Proof(sigma.to_bytes(), mu)


# ---------------------------------------------------------------- verify


def _rhs_point(
    name: bytes, challenge: Challenge, mu: list[int]
) -> G1Point:
    """Π_c H(name‖i_c)^{v_c} · Π_j u_j^{μ_j}"""
    us = u_generators(len(mu))
    acc = G1Point.infinity()
    for v, i in zip(challenge.coefficients(), challenge.indices):
        acc = acc + chunk_point(name, i).mul(v)
    for u, m in zip(us, mu):
        if m:
            acc = acc + u.mul(m)
    return acc


def verify(
    pk: bytes,
    name: bytes,
    challenge: Challenge,
    proof: Podr2Proof,
    s: int | None = None,
) -> bool:
    """Single-proof pairing check.  `s` pins the expected sector count; a
    proof of any other μ width is rejected outright (malformed-input
    handling must be identical across backends — consensus-critical)."""
    try:
        sigma = G1Point.from_bytes(proof.sigma)
        pk_point = G2Point.from_bytes(pk)
    except ValueError:
        return False
    if s is not None and len(proof.mu) != s:
        return False
    if any(not 0 <= m < R for m in proof.mu):
        return False
    rhs = _rhs_point(name, challenge, proof.mu)
    return bls.pairing_check([(sigma, -bls.G2_GENERATOR), (rhs, pk_point)])


@dataclass
class BatchItem:
    name: bytes
    challenge: Challenge
    proof: Podr2Proof


@lru_cache(maxsize=256)
def _challenge_bytes(challenge: Challenge) -> bytes:
    """The challenge's transcript contribution, packed once.  A live
    audit round shares ONE Challenge across every proof of the batch, so
    the per-proof transcript loop re-serialized the same 47 (index,
    random) pairs N times; Challenge is a frozen (hashable) dataclass,
    so the packed bytes cache by value.  Same zip-truncation semantics
    as the rest of the scheme."""
    return b"".join(
        i.to_bytes(4, "little") + v
        for i, v in zip(challenge.indices, challenge.randoms)
    )


def batch_transcript(
    seed: bytes,
    items: list["BatchItem"],
    encodings: list[bytes] | None = None,
) -> bytes:
    """Fiat–Shamir transcript binding the ρ weights to the proofs.

    The small-exponent batch test is only sound when the prover cannot
    predict the weights; hashing every (name, challenge, proof) into the
    seed makes ρ depend on the submitted proofs themselves, so cancelling
    deviations cannot be pre-computed.

    `encodings` optionally supplies precomputed proof.encode() blobs so
    one shared encode pass can feed both this transcript and the
    verifier's μ word packing (proof/frontend.py); the digest is
    byte-identical either way (blake2b streaming is concatenation-
    associative), asserted in tests/test_proof_hotpath.py."""
    h = hashlib.blake2b(digest_size=32)
    h.update(RHO_DST)
    h.update(seed)
    sha256 = hashlib.sha256
    for k, it in enumerate(items):
        h.update(sha256(it.name).digest())
        h.update(_challenge_bytes(it.challenge))
        h.update(
            encodings[k] if encodings is not None else it.proof.encode()
        )
    return h.digest()


def batch_rho(transcript: bytes, count: int) -> list[int]:
    """Deterministic 128-bit batch weights from a transcript digest (both
    backends derive identical combinations from identical inputs).  The
    (RHO_DST ‖ transcript) prefix is absorbed once and copied per weight
    — hash-state copy + one 8-byte tail instead of re-hashing the prefix
    N times; byte-identical to the one-shot form."""
    prefix = hashlib.blake2b(digest_size=16)
    prefix.update(RHO_DST)
    prefix.update(transcript)
    out = []
    for b in range(count):
        h = prefix.copy()
        h.update(b.to_bytes(8, "little"))
        out.append(int.from_bytes(h.digest(), "little") | 1)  # nonzero
    return out


def batch_verify(
    pk: bytes,
    items: list[BatchItem],
    seed: bytes,
    u_exponents: list[int] | None = None,
    s: int | None = None,
) -> bool:
    """One combined check for N proofs under the same pk (module docstring
    equation).  Returns False if ANY proof in the batch is invalid; callers
    needing per-proof verdicts bisect or fall back to verify().

    `u_exponents` lets a backend supply the device-computed
    Σ_b ρ_b μ_bj vector (same ρ derivation) — the single seam where the
    xla backend differs from this host reference.  `s` pins the expected
    sector count; when None it is derived from the first item (all items
    must agree either way)."""
    if not items:
        return True
    try:
        pk_point = G2Point.from_bytes(pk)
        sigmas = [G1Point.from_bytes(it.proof.sigma) for it in items]
    except ValueError:
        return False
    if s is None:
        s = len(items[0].proof.mu)
    if any(len(it.proof.mu) != s for it in items):
        return False
    if any(not 0 <= m < R for it in items for m in it.proof.mu):
        return False
    rhos = batch_rho(batch_transcript(seed, items), len(items))

    # left: Π σ_b^{ρ_b}
    lhs = G1Point.infinity()
    for sigma, rho in zip(sigmas, rhos):
        lhs = lhs + sigma.mul(rho)

    # right, H side: Π_{b,c} H_{b,c}^{ρ_b v_c}
    rhs = G1Point.infinity()
    for it, rho in zip(items, rhos):
        for v, i in zip(it.challenge.coefficients(), it.challenge.indices):
            rhs = rhs + chunk_point(it.name, i).mul(rho * v % R)

    # right, u side: Π_j u_j^{Σ_b ρ_b μ_bj} — the TPU matmul term.
    us = u_generators(s)
    if u_exponents is None:
        u_exponents = []
        for j in range(s):
            e = 0
            for it, rho in zip(items, rhos):
                e = (e + rho * it.proof.mu[j]) % R
            u_exponents.append(e)
    for u, e in zip(us, u_exponents):
        if e:
            rhs = rhs + u.mul(e)

    return bls.pairing_check([(lhs, -bls.G2_GENERATOR), (rhs, pk_point)])


# ---------------------------------------------------------------- idle data


def filler_data(filler_hash: bytes, params: Podr2Params) -> bytes:
    """Deterministic idle-space filler content: expandable from its hash so
    idle proofs need no stored plaintext (reference fillers are 8 MiB
    pseudo-files, c-pallets/file-bank/src/lib.rs:830-836)."""
    out = bytearray()
    counter = 0
    while len(out) < params.fragment_bytes:
        out.extend(
            hashlib.blake2b(
                b"cess/filler" + filler_hash + counter.to_bytes(8, "little"),
                digest_size=64,
            ).digest()
        )
        counter += 1
    return bytes(out[: params.fragment_bytes])
