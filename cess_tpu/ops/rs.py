"""Reed-Solomon erasure coding as JAX/TPU kernels.

Two device paths, both bit-identical to the numpy reference in ops/gf256.py:

1. **bitplane** (default, MXU path): a GF(256) matrix-vector product is a
   GF(2)-linear map on the bit-planes of the data, so RS encode becomes a
   dense (8m x 8k) @ (8k x n) 0/1 int8 matmul reduced mod 2 — exactly the
   shape the TPU MXU is built for.  No gathers, no scalar loops; throughput
   scales with matmul peak, not vector-lane lookup speed.

2. **gather**: XOR-accumulated rows of the 256x256 GF multiplication table.
   Simpler, good on CPU; used as an on-device cross-check.

Decode = encode with a host-computed k x k inverse (the inversion is O(k^3)
over tiny k and stays on host; the O(k * n) byte work runs on device).

Reference behavior being re-expressed: segment -> fragment erasure coding with
1.5x redundancy (reference: runtime/src/lib.rs:1025, file-bank/src/lib.rs:468)
and the RS(12,4) / RS(2,1) geometries from BASELINE.json configs.
"""

from __future__ import annotations

from functools import lru_cache, reduce

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

# ---------------------------------------------------------------- helpers


def _bits_from_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """(r, n) uint8 -> (8r, n) int8 little-endian bit-planes."""
    r, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & 1  # (r, 8, n)
    return bits.reshape(8 * r, n).astype(jnp.int8)


def _bytes_from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, n) int -> (r, n) uint8, little-endian bit order."""
    r8, n = bits.shape
    r = r8 // 8
    b = bits.reshape(r, 8, n).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :, None]
    return jnp.sum(b * weights, axis=1, dtype=jnp.uint8)


@lru_cache(maxsize=64)
def _bit_matrix_cached(matrix_bytes: bytes, rows: int, cols: int) -> np.ndarray:
    m = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(rows, cols)
    return gf256.bit_matrix(m)


# ---------------------------------------------------------------- kernels


@jax.jit
def _matmul_gf_bitplane(bitmat: jnp.ndarray, data: jnp.ndarray):
    """GF(256) matrix product via mod-2 int8 matmul.

    bitmat: (8m, 8k) int8 0/1 — host-expanded GF(2) matrix
    data:   (k, n) uint8
    returns (m, n) uint8
    """
    bits = _bits_from_bytes(data)  # (8k, n) int8
    acc = jax.lax.dot_general(
        bitmat,
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (8m, n) int32, each entry <= 8k < 2^31
    return _bytes_from_bits(acc & 1)


def _matmul_gf_gather(matrix: jnp.ndarray, data: jnp.ndarray, mul_table: jnp.ndarray):
    """GF(256) matrix product via MUL_TABLE row gathers.

    matrix: (m, k) uint8, data: (k, n) uint8 -> (m, n) uint8
    """
    k = data.shape[0]

    def one_row(row):  # row: (k,) uint8
        terms = [mul_table[row[i], :][data[i]] for i in range(k)]
        return reduce(jnp.bitwise_xor, terms)

    return jax.vmap(one_row)(matrix)


_gather_jit = jax.jit(_matmul_gf_gather)
_gather_batch_jit = jax.jit(jax.vmap(_matmul_gf_gather, in_axes=(None, 0, None)))
_bitplane_batch_jit = jax.jit(jax.vmap(_matmul_gf_bitplane, in_axes=(None, 0)))


# ---------------------------------------------------------------- public API


class RSCode:
    """Systematic RS(k, m) over GF(2^8) with Cauchy parity rows.

    encode: (k, n) data shards -> (m, n) parity shards
    reconstruct: any k of the k+m shards -> original k data shards
    Batched variants vmap over a leading batch axis (BASELINE config 2:
    1k-file RS(12,4) encode batches).
    """

    def __init__(self, k: int, m: int, path: str = "bitplane") -> None:
        if path not in ("bitplane", "gather"):
            raise ValueError(f"unknown RS path {path!r}")
        self.k, self.m, self.path = k, m, path
        self._parity = gf256.cauchy_matrix(k, m)
        self._gen = gf256.encode_matrix(k, m)
        self._mul_table = jnp.asarray(gf256.MUL_TABLE)
        self._parity_dev = jnp.asarray(self._parity)
        self._parity_bits = jnp.asarray(
            _bit_matrix_cached(self._parity.tobytes(), m, k), dtype=jnp.int8
        )

    # -- encode ---------------------------------------------------------

    def encode(self, data) -> jnp.ndarray:
        """(k, n) uint8 -> (m, n) uint8 parity."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if self.path == "bitplane":
            return _matmul_gf_bitplane(self._parity_bits, data)
        return _gather_jit(self._parity_dev, data, self._mul_table)

    def encode_batch(self, data) -> jnp.ndarray:
        """(b, k, n) -> (b, m, n)."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if self.path == "bitplane":
            return _bitplane_batch_jit(self._parity_bits, data)
        return _gather_batch_jit(self._parity_dev, data, self._mul_table)

    # -- decode ---------------------------------------------------------

    def recovery_matrix(self, present: list[int]) -> np.ndarray:
        """Host-side k x k inverse for the surviving shard set."""
        if len(present) < self.k:
            raise ValueError(
                f"need {self.k} shards to recover, have {len(present)}"
            )
        sub = self._gen[np.asarray(present[: self.k])]
        return gf256.mat_inv(sub)

    def reconstruct(self, shards, present: list[int]) -> jnp.ndarray:
        """shards (>=k, n) rows matching `present` global indices -> (k, n) data."""
        inv = self.recovery_matrix(present)
        shards = jnp.asarray(shards, dtype=jnp.uint8)[: self.k]
        if self.path == "bitplane":
            bits = jnp.asarray(
                _bit_matrix_cached(
                    np.ascontiguousarray(inv).tobytes(), self.k, self.k
                ),
                dtype=jnp.int8,
            )
            return _matmul_gf_bitplane(bits, shards)
        return _gather_jit(jnp.asarray(inv), shards, self._mul_table)

    def reconstruct_batch(self, shards, present: list[int]) -> jnp.ndarray:
        """(b, >=k, n) with one shared erasure pattern -> (b, k, n)."""
        inv = self.recovery_matrix(present)
        shards = jnp.asarray(shards, dtype=jnp.uint8)[:, : self.k]
        if self.path == "bitplane":
            bits = jnp.asarray(
                _bit_matrix_cached(
                    np.ascontiguousarray(inv).tobytes(), self.k, self.k
                ),
                dtype=jnp.int8,
            )
            return _bitplane_batch_jit(bits, shards)
        return _gather_batch_jit(jnp.asarray(inv), shards, self._mul_table)


# Protocol geometry (reference: primitives/common/src/lib.rs:60-62 — 16 MiB
# segments, 8 MiB fragments, i.e. k=2 data + m=1 parity).
SEGMENT_K = 2
SEGMENT_M = 1


def segment_code(path: str = "bitplane") -> RSCode:
    return RSCode(SEGMENT_K, SEGMENT_M, path=path)
