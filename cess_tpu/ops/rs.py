"""Reed-Solomon erasure coding as a streamed, mesh-sharded JAX data plane.

Two device kernels, both bit-identical to the numpy reference in
ops/gf256.py (tests/test_rs_hotpath.py pins every path against
`gf256.rs_encode_ref` / `rs_decode_ref`):

1. **bitplane** (MXU path, default on TPU): a GF(256) matrix-vector
   product is a GF(2)-linear map on the bit-planes of the data, so RS
   encode becomes a dense (8m x 8k) @ (8k x n) 0/1 int8 matmul reduced
   mod 2 — exactly the shape the TPU MXU is built for.

2. **gather** (default elsewhere): XOR-accumulated rows of the 256x256
   GF multiplication table.  On CPU hosts it avoids the 8× bit-plane
   blow-up and measures ~6× faster than bitplane at segment geometry.

Decode = encode with a host-computed k x k inverse (O(k^3) over tiny k,
cached per survivor mask; the O(k * n) byte work runs on device).

The data plane around the kernels (the part the north-star bench pays
for — RS-reconstructing 10 GiB is half the denominator):

* **One-shape tiled kernels** — streams process fixed-width `tile`
  slices of the byte axis (padded tail), so a multi-GiB stream at
  fixed (k, m, tile) traces each kernel exactly ONCE per process.
  `COMPILE_COUNTS` increments at trace time (same pattern as
  proof/fused.py) and the `rs_hotpath` CI gate asserts the invariant.
* **RSStream** — chunked transfer/compute overlap: the host packs and
  `device_put`s tile t+1 while tile t's matmul runs under JAX async
  dispatch, with buffer donation on the reconstruct path (TPU).
  bench.py, parallel/epoch_sim.py's RS stage, and the chain sim's
  upload/recovery helpers (chain/node.py) all drive it.
* **Mesh sharding in the core API** — `mesh=` on the batch calls
  shards the segment axis over a `jax.sharding.Mesh` via shard_map
  (embarrassingly parallel, no collectives); `RSStream.run` shards
  the byte axis of a single huge segment the same way.  The 8-device
  path is the same code tier-1 tests exercise on the virtual CPU mesh.
* **Grouped per-pattern recovery** — real networks lose *different*
  shards per segment; `reconstruct_batch` (and `RSStream.run_batch`)
  accept a per-segment survivor list, group segments by survivor mask
  (one host `mat_inv` per distinct mask), and run one batched matmul
  stream per group — bit-identical to the per-item numpy reference.
* **Stage histograms** — streams observe the always-on
  `cess_rs_{pack,matmul,dispatch_wait,unpack}_seconds` histograms
  (rs_stage_registry, merged into node `system_metrics`), mirroring
  the proof pipelines; docs/perf.md explains how to read the overlap.

Reference behavior being re-expressed: segment -> fragment erasure
coding with 1.5x redundancy (reference: runtime/src/lib.rs:1025,
file-bank/src/lib.rs:468) and the RS(12,4) / RS(2,1) geometries from
BASELINE.json configs.
"""

from __future__ import annotations

import os
import threading
import time as _time
from functools import lru_cache, reduce

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from . import gf256

# Byte-axis tile width for streams (CESS_RS_TILE overrides).  1 MiB
# keeps the working set L2-resident on CPU hosts (the whole-array path
# materialised 8× bit-plane intermediates per pass) and amortises
# dispatch overhead on TPU; sub-tile arrays pad to a power of two so
# one-shot calls stay bounded at O(log n) compiles instead of one per
# distinct width.
TILE = int(os.environ.get("CESS_RS_TILE", str(1 << 20)))
# Segment-axis slab for batched streams (CESS_RS_SLAB overrides):
# every dispatched slab has exactly this many segments (padded tail),
# so grouped recovery reuses ONE executable across groups of any size.
SLAB = int(os.environ.get("CESS_RS_SLAB", "32"))
_MIN_WIDTH = 16  # floor of the pow2 bucket for tiny one-shot arrays

# Trace-time counters: jax re-traces only on a new argument-shape
# signature, so each count is the number of distinct compiled
# executables this process built for that kernel — the measurable form
# of the one-shape invariant (tests/test_rs_hotpath.py asserts a
# multi-tile stream traces its kernel exactly once).
COMPILE_COUNTS = {"bitplane": 0, "gather": 0}


# ------------------------------------------------------- stage telemetry
#
# Always-on per-stage histograms of the streamed data plane, the RS
# counterpart of proof/xla_backend.py's proof_stage_registry: `pack` is
# host tile slicing + async upload, `matmul` the async kernel
# dispatches, `dispatch_wait` the final block on device results (the
# device time host packing failed to hide), `unpack` the device→host
# pulls + reassembly.  The registry is process-wide and merged into
# node `system_metrics` (node/rpc.py); CESS_STAGE_METRICS=0 switches
# the marks off for A/B measurement, same knob as the proof stages.

RS_STAGE_NAMES = ("pack", "matmul", "dispatch_wait", "unpack")
STAGE_METRICS_ENABLED = os.environ.get(
    "CESS_STAGE_METRICS", "1") not in ("0", "false", "off")

_rs_stage_lock = threading.Lock()
_rs_stage_registry = None
_rs_stage_hists: dict = {}
_rs_stage_counters: dict = {}

_RS_STAGE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)


def rs_stage_registry():
    """The process-wide metrics registry for the RS data plane (created
    on first use; node/metrics is imported lazily to keep the ops↔node
    package import graph acyclic)."""
    global _rs_stage_registry
    with _rs_stage_lock:
        if _rs_stage_registry is None:
            from ..node import metrics as m

            reg = m.Registry()
            for name in RS_STAGE_NAMES:
                _rs_stage_hists[name] = m.Histogram(
                    f"cess_rs_{name}_seconds",
                    f"RS stream {name} stage time",
                    buckets=_RS_STAGE_BUCKETS, registry=reg)
            _rs_stage_counters["bytes"] = m.Counter(
                "cess_rs_bytes_total",
                "payload bytes through streamed RS kernels", reg)
            _rs_stage_counters["streams"] = m.Counter(
                "cess_rs_streams_total",
                "RSStream passes executed", reg)
            _rs_stage_counters["seconds"] = m.Counter(
                "cess_rs_seconds_total",
                "wall-clock seconds spent in RS streams", reg)
            _rs_stage_registry = reg
    return _rs_stage_registry


def _observe_rs_stage(name: str, seconds: float) -> None:
    rs_stage_registry()
    _rs_stage_hists[name].observe(seconds)


# ------------------------------------------------- device-constant caches
#
# Module-level, keyed by code geometry: RSCode.__init__ used to
# re-upload the 64 KiB MUL_TABLE and re-expand/re-upload the parity
# bit-matrix on every construction — role clients building a code per
# file paid it per file.  Constructing RSCode(k, m) is now free after
# the first.


@lru_cache(maxsize=1)
def _mul_table_dev() -> jnp.ndarray:
    return jnp.asarray(gf256.MUL_TABLE)


@lru_cache(maxsize=64)
def _code_matrices(k: int, m: int) -> tuple[np.ndarray, np.ndarray]:
    """Host (parity, generator) for RS(k, m)."""
    return gf256.cauchy_matrix(k, m), gf256.encode_matrix(k, m)


@lru_cache(maxsize=64)
def _parity_dev(k: int, m: int) -> jnp.ndarray:
    return jnp.asarray(_code_matrices(k, m)[0])


@lru_cache(maxsize=64)
def _parity_bits_dev(k: int, m: int) -> jnp.ndarray:
    parity = _code_matrices(k, m)[0]
    return _bits_dev(parity.tobytes(), m, k)


@lru_cache(maxsize=64)
def _bit_matrix_cached(matrix_bytes: bytes, rows: int, cols: int) -> np.ndarray:
    m = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(rows, cols)
    return gf256.bit_matrix(m)


@lru_cache(maxsize=256)
def _bits_dev(matrix_bytes: bytes, rows: int, cols: int) -> jnp.ndarray:
    """Device int8 upload of a GF(2)-expanded matrix (cached: recovery
    streams reuse one upload per survivor mask)."""
    return jnp.asarray(
        _bit_matrix_cached(matrix_bytes, rows, cols), dtype=jnp.int8
    )


@lru_cache(maxsize=256)
def _matrix_dev(matrix_bytes: bytes, rows: int, cols: int) -> jnp.ndarray:
    return jnp.asarray(
        np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(rows, cols)
    )


@lru_cache(maxsize=4096)
def _inv_cached(k: int, m: int, present: tuple[int, ...]) -> np.ndarray:
    """Host k x k recovery inverse for one survivor mask (O(k^3) over
    tiny k — cached because grouped recovery hits few distinct masks)."""
    gen = _code_matrices(k, m)[1]
    return gf256.mat_inv(gen[np.asarray(present)])


# ---------------------------------------------------------------- helpers


def _bits_from_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """(r, n) uint8 -> (8r, n) int8 little-endian bit-planes."""
    r, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & 1  # (r, 8, n)
    return bits.reshape(8 * r, n).astype(jnp.int8)


def _bytes_from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, n) int -> (r, n) uint8, little-endian bit order."""
    r8, n = bits.shape
    r = r8 // 8
    b = bits.reshape(r, 8, n).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :, None]
    return jnp.sum(b * weights, axis=1, dtype=jnp.uint8)


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# ---------------------------------------------------------------- kernels


def _matmul_gf_bitplane(bitmat: jnp.ndarray, data: jnp.ndarray):
    """GF(256) matrix product via mod-2 int8 matmul.

    bitmat: (8m, 8k) int8 0/1 — host-expanded GF(2) matrix
    data:   (k, n) uint8
    returns (m, n) uint8
    """
    COMPILE_COUNTS["bitplane"] += 1  # trace-time: one per compiled shape
    bits = _bits_from_bytes(data)  # (8k, n) int8
    acc = jax.lax.dot_general(
        bitmat,
        bits,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (8m, n) int32, each entry <= 8k < 2^31
    return _bytes_from_bits(acc & 1)


def _matmul_gf_gather(matrix: jnp.ndarray, data: jnp.ndarray, mul_table: jnp.ndarray):
    """GF(256) matrix product via MUL_TABLE row gathers.

    matrix: (m, k) uint8, data: (k, n) uint8 -> (m, n) uint8
    """
    COMPILE_COUNTS["gather"] += 1  # trace-time: one per compiled shape
    k = data.shape[0]

    def one_row(row):  # row: (k,) uint8
        terms = [mul_table[row[i], :][data[i]] for i in range(k)]
        return reduce(jnp.bitwise_xor, terms)

    return jax.vmap(one_row)(matrix)


def _donate_ok() -> bool:
    """Buffer donation only helps (and only stays warning-free) on TPU;
    CPU/GPU emulation paths run the plain kernels."""
    return jax.default_backend() == "tpu"


@lru_cache(maxsize=8)
def _kernel_jit(path: str, donate: bool):
    """Module-cached jitted kernel.  `donate` hands the data buffer to
    XLA for output reuse — valid when in/out shapes match (the k -> k
    reconstruct path), a free HBM saving on GiB streams."""
    fn = _matmul_gf_bitplane if path == "bitplane" else _matmul_gf_gather
    return jax.jit(fn, donate_argnums=(1,) if donate else ())


@lru_cache(maxsize=8)
def _batch_kernel_jit(path: str, donate: bool):
    if path == "bitplane":
        fn = jax.vmap(_matmul_gf_bitplane, in_axes=(None, 0))
    else:
        fn = jax.vmap(_matmul_gf_gather, in_axes=(None, 0, None))
    return jax.jit(fn, donate_argnums=(1,) if donate else ())


@lru_cache(maxsize=32)
def _sharded_batch_fn(mesh, path: str):
    """Batch-axis mesh sharding: segments split over devices, no
    collectives (folds parallel/epoch_sim's former _rs_recover_sharded
    into the core API).  Cached per (mesh, path) — rebuilding the jit
    wrapper per call would re-trace every call."""
    from jax.experimental.shard_map import shard_map

    axis = mesh.axis_names[0]
    if path == "bitplane":
        fn = shard_map(
            jax.vmap(_matmul_gf_bitplane, in_axes=(None, 0)),
            mesh=mesh,
            in_specs=(P(None, None), P(axis, None, None)),
            out_specs=P(axis, None, None),
            check_rep=False,
        )
    else:
        fn = shard_map(
            jax.vmap(_matmul_gf_gather, in_axes=(None, 0, None)),
            mesh=mesh,
            in_specs=(
                P(None, None), P(axis, None, None), P(None, None)
            ),
            out_specs=P(axis, None, None),
            check_rep=False,
        )
    return jax.jit(fn)


@lru_cache(maxsize=32)
def _sharded_cols_fn(mesh, path: str):
    """Byte-axis mesh sharding: one huge segment's columns split over
    devices (the single-giant-file recovery shape)."""
    from jax.experimental.shard_map import shard_map

    axis = mesh.axis_names[0]
    if path == "bitplane":
        fn = shard_map(
            _matmul_gf_bitplane,
            mesh=mesh,
            in_specs=(P(None, None), P(None, axis)),
            out_specs=P(None, axis),
            check_rep=False,
        )
    else:
        fn = shard_map(
            _matmul_gf_gather,
            mesh=mesh,
            in_specs=(P(None, None), P(None, axis), P(None, None)),
            out_specs=P(None, axis),
            check_rep=False,
        )
    return jax.jit(fn)


def default_path() -> str:
    """bitplane rides the MXU on TPU; the gather kernel avoids the 8×
    bit-plane memory blow-up everywhere else (measured ~6× faster at
    segment geometry on CPU hosts — BENCH_r07)."""
    return "bitplane" if jax.default_backend() == "tpu" else "gather"


# ------------------------------------------------------------- validation


def check_present(present, k: int, m: int) -> tuple[int, ...]:
    """Validate one survivor list and return the k-row prefix actually
    consumed.  Duplicate or out-of-range indices used to surface as a
    late 'singular GF(256) matrix' (or silently selected wrong rows);
    they are a caller bug and fail loudly up front."""
    idx = [int(i) for i in present]
    if len(idx) < k:
        raise ValueError(f"need {k} shards to recover, have {len(idx)}")
    idx = idx[:k]
    for i in idx:
        if not 0 <= i < k + m:
            raise ValueError(
                f"survivor index {i} out of range for RS({k},{m}) "
                f"(valid: 0..{k + m - 1})"
            )
    if len(set(idx)) != k:
        raise ValueError(f"duplicate survivor indices in {idx}")
    return tuple(idx)


def _is_per_segment(present) -> bool:
    """True when `present` is a per-segment list of survivor lists."""
    if isinstance(present, np.ndarray):
        return present.ndim == 2
    return bool(len(present)) and not np.isscalar(present[0]) and not isinstance(
        present[0], (int, np.integer)
    )


def _check_shards(a, min_rows: int, batched: bool) -> None:
    shape = getattr(a, "shape", None)
    want = 3 if batched else 2
    if shape is None or len(shape) != want:
        raise ValueError(
            f"shard array must be {want}-D "
            f"{'(B, rows, n)' if batched else '(rows, n)'}, got shape {shape}"
        )
    if 0 in shape:
        raise ValueError(f"empty shard array (shape {shape})")
    rows = shape[1] if batched else shape[0]
    if rows < min_rows:
        raise ValueError(f"need {min_rows} shard rows, have {rows}")


# ---------------------------------------------------------------- public API


class RSCode:
    """Systematic RS(k, m) over GF(2^8) with Cauchy parity rows.

    encode: (k, n) data shards -> (m, n) parity shards
    reconstruct: any k of the k+m shards -> original k data shards
    Batched variants take a leading segment axis and an optional
    `mesh=` to shard it (BASELINE configs 2 and 5); `present` on the
    batch form may be one shared survivor list or one list per segment
    (grouped per-pattern recovery).  GiB-scale host arrays stream
    through RSStream.

    path: "bitplane" (MXU matmul), "gather" (table gathers), or "auto"
    (bitplane on TPU, gather elsewhere).  All paths are bit-identical.
    """

    def __init__(
        self, k: int, m: int, path: str = "bitplane",
        tile: int | None = None,
    ) -> None:
        if path == "auto":
            path = default_path()
        if path not in ("bitplane", "gather"):
            raise ValueError(f"unknown RS path {path!r}")
        if k < 1 or m < 1:
            raise ValueError(f"RS(k={k}, m={m}) needs k >= 1 and m >= 1")
        if k + m > gf256.FIELD:
            raise ValueError("k + m must be <= 256")
        self.k, self.m, self.path = k, m, path
        self.tile = int(tile) if tile else TILE
        self._parity, self._gen = _code_matrices(k, m)
        self._mul_table = _mul_table_dev()
        self._parity_dev = _parity_dev(k, m)
        self._parity_bits = _parity_bits_dev(k, m)

    # -- kernel dispatch ------------------------------------------------

    def _mat_dev(self, mat_host: np.ndarray) -> jnp.ndarray:
        """Device form of a host GF(256) matrix for this code's path."""
        raw = np.ascontiguousarray(mat_host)
        r, c = raw.shape
        if self.path == "bitplane":
            return _bits_dev(raw.tobytes(), r, c)
        return _matrix_dev(raw.tobytes(), r, c)

    def _kernel(self, mat_dev, data, *, donate: bool = False):
        don = donate and _donate_ok()
        if self.path == "bitplane":
            return _kernel_jit("bitplane", don)(mat_dev, data)
        return _kernel_jit("gather", don)(mat_dev, data, self._mul_table)

    def _batch_kernel(self, mat_dev, data, mesh=None, *, donate=False):
        if mesh is not None:
            fn = _sharded_batch_fn(mesh, self.path)
            if self.path == "bitplane":
                return fn(mat_dev, data)
            return fn(mat_dev, data, self._mul_table)
        don = donate and _donate_ok()
        if self.path == "bitplane":
            return _batch_kernel_jit("bitplane", don)(mat_dev, data)
        return _batch_kernel_jit("gather", don)(
            mat_dev, data, self._mul_table
        )

    def _apply(self, mat_host: np.ndarray, data, mesh=None):
        """mat @ data over the byte axis with one-shape padding: widths
        below `tile` bucket to a power of two (bounded compiles);
        wider arrays run fixed `tile` slices via the batched kernel."""
        mat_dev = self._mat_dev(mat_host)
        n = data.shape[-1]
        if mesh is not None:
            n_dev = mesh.devices.size
            pad_n = -n % n_dev
            xp = jnp.asarray(data, jnp.uint8)
            if pad_n:
                xp = jnp.pad(xp, [(0, 0), (0, pad_n)])
            fn = _sharded_cols_fn(mesh, self.path)
            out = (
                fn(mat_dev, xp)
                if self.path == "bitplane"
                else fn(mat_dev, xp, self._mul_table)
            )
            return out[..., :n] if pad_n else out
        tile = self.tile
        if n > tile:
            tiles = -(-n // tile)
            xp = jnp.pad(
                jnp.asarray(data, jnp.uint8), [(0, 0), (0, tiles * tile - n)]
            )
            stacked = jnp.moveaxis(
                xp.reshape(data.shape[0], tiles, tile), 1, 0
            )  # (tiles, rows, tile)
            out = self._batch_kernel(mat_dev, stacked)
            out = jnp.moveaxis(out, 0, 1).reshape(mat_host.shape[0], -1)
            return out[..., :n]
        width = max(_pow2(n), _MIN_WIDTH)
        if width != n:
            xp = jnp.pad(jnp.asarray(data, jnp.uint8), [(0, 0), (0, width - n)])
            return self._kernel(mat_dev, xp)[..., :n]
        return self._kernel(mat_dev, jnp.asarray(data, jnp.uint8))

    def _apply_batch(self, mat_host: np.ndarray, data, mesh=None):
        """Batched mat @ data with the segment axis pow2-bucketed (and
        rounded to the mesh size when sharded)."""
        mat_dev = self._mat_dev(mat_host)
        b = data.shape[0]
        bp = max(_pow2(b), 1)
        if mesh is not None:
            n_dev = mesh.devices.size
            bp = -(-bp // n_dev) * n_dev
        xp = jnp.asarray(data, jnp.uint8)
        if bp != b:
            xp = jnp.pad(xp, [(0, bp - b), (0, 0), (0, 0)])
        out = self._batch_kernel(mat_dev, xp, mesh=mesh)
        return out[:b] if bp != b else out

    # -- encode ---------------------------------------------------------

    def encode(self, data, mesh=None) -> jnp.ndarray:
        """(k, n) uint8 -> (m, n) uint8 parity.  `mesh` shards the byte
        axis over devices (single huge segment)."""
        _check_shards(data, self.k, batched=False)
        return self._apply(self._parity, data, mesh=mesh)

    def encode_batch(self, data, mesh=None) -> jnp.ndarray:
        """(b, k, n) -> (b, m, n).  `mesh` shards the segment axis."""
        _check_shards(data, self.k, batched=True)
        return self._apply_batch(self._parity, data, mesh=mesh)

    # -- decode ---------------------------------------------------------

    def recovery_matrix(self, present) -> np.ndarray:
        """Host-side k x k inverse for the surviving shard set (indices
        validated; cached per distinct mask)."""
        return _inv_cached(
            self.k, self.m, check_present(present, self.k, self.m)
        ).copy()

    def reconstruct(self, shards, present, mesh=None) -> jnp.ndarray:
        """shards (>=k, n) rows matching `present` global indices ->
        (k, n) data.  `mesh` shards the byte axis."""
        _check_shards(shards, self.k, batched=False)
        mask = check_present(present, self.k, self.m)
        inv = _inv_cached(self.k, self.m, mask)
        return self._apply(inv, jnp.asarray(shards)[: self.k], mesh=mesh)

    def reconstruct_batch(self, shards, present, mesh=None):
        """(b, >=k, n) -> (b, k, n).

        `present` is either ONE survivor list shared by every segment,
        or a per-segment list of survivor lists — segments are then
        grouped by survivor mask (one host inverse per distinct mask,
        one batched matmul per group; returns host uint8, assembled in
        segment order, bit-identical to per-item gf256.rs_decode_ref).
        """
        _check_shards(shards, self.k, batched=True)
        if _is_per_segment(present):
            return RSStream(self, present=present, mesh=mesh).run_batch(
                np.asarray(shards, dtype=np.uint8)
            )
        mask = check_present(present, self.k, self.m)
        inv = _inv_cached(self.k, self.m, mask)
        return self._apply_batch(
            inv, jnp.asarray(shards)[:, : self.k], mesh=mesh
        )


# ---------------------------------------------------------------- streams


class RSStream:
    """Streamed RS over GiB-scale host arrays with transfer/compute
    overlap.

    The host packs (slices, pads, `device_put`s) tile t+1 while tile
    t's matmul executes under JAX async dispatch — nothing blocks on
    device values until every tile is in flight, then one
    block_until_ready drains the pipeline and the outputs are pulled.
    `present=None` streams encode; a survivor list (or per-segment
    lists for `run_batch`) streams reconstruction, with buffer
    donation on TPU (in/out shapes match on the k -> k decode).

    Stage seconds land in the always-on cess_rs_* histograms and, when
    a `stages` dict is given, accumulate there per call — `pack` vs
    `dispatch_wait` is the overlap read, exactly as in the fused proof
    pipeline (docs/perf.md).
    """

    def __init__(
        self, code: RSCode, *, present=None, mesh=None,
        tile: int | None = None, slab: int | None = None,
        stages: dict | None = None,
    ) -> None:
        self.code = code
        self.mesh = mesh
        self.tile = int(tile) if tile else code.tile
        slab = int(slab) if slab else SLAB
        if mesh is not None:
            # shard_map splits the tile / slab axis over devices, so
            # both must divide the mesh size
            n_dev = mesh.devices.size
            slab = -(-slab // n_dev) * n_dev
            self.tile = -(-self.tile // n_dev) * n_dev
        self.slab = slab
        self.stages = stages
        self.present = present
        if present is not None and not _is_per_segment(present):
            # validate the shared mask once, up front
            check_present(present, code.k, code.m)

    # -- telemetry ------------------------------------------------------

    def _mark(self, name: str, t0: float) -> float:
        now = _time.perf_counter()
        if self.stages is not None:
            self.stages[name] = self.stages.get(name, 0.0) + (now - t0)
        if STAGE_METRICS_ENABLED:
            _observe_rs_stage(name, now - t0)
        return now

    def _account(self, nbytes: int, t_start: float) -> None:
        if STAGE_METRICS_ENABLED:
            rs_stage_registry()
            _rs_stage_counters["bytes"].inc(nbytes)
            _rs_stage_counters["streams"].inc()
            _rs_stage_counters["seconds"].inc(
                _time.perf_counter() - t_start
            )

    # -- byte-axis stream ----------------------------------------------

    def _op_matrix(self) -> np.ndarray:
        code = self.code
        if self.present is None:
            return code._parity
        return _inv_cached(
            code.k, code.m, check_present(self.present, code.k, code.m)
        )

    def run(self, data: np.ndarray) -> np.ndarray:
        """(rows, n) host uint8 stream -> (out_rows, n) host uint8.

        rows = k for encode; the first k survivor rows (matching
        `present`) for reconstruct.  The byte axis is processed in
        fixed `tile` slices (padded tail) — ONE kernel shape per
        stream, asserted by COMPILE_COUNTS.
        """
        code = self.code
        t_start = _time.perf_counter()
        _check_shards(data, code.k, batched=False)
        if self.present is None and data.shape[0] != code.k:
            raise ValueError(
                f"encode stream needs exactly {code.k} data rows, "
                f"got {data.shape[0]}"
            )
        data = np.asarray(data, dtype=np.uint8)[: code.k]
        mat = self._op_matrix()
        mat_dev = code._mat_dev(mat)
        n = data.shape[1]
        tile = self.tile
        donate = self.present is not None
        t0 = t_start
        outs = []
        pulled = []
        for off in range(0, n, tile):
            chunk = data[:, off : off + tile]
            if chunk.shape[1] != tile:  # padded tail: one shape only
                padded = np.zeros((code.k, tile), dtype=np.uint8)
                padded[:, : chunk.shape[1]] = chunk
                chunk = padded
            dev = jax.device_put(np.ascontiguousarray(chunk))
            t0 = self._mark("pack", t0)
            if self.mesh is not None:
                fn = _sharded_cols_fn(self.mesh, code.path)
                out = (
                    fn(mat_dev, dev)
                    if code.path == "bitplane"
                    else fn(mat_dev, dev, code._mul_table)
                )
            else:
                out = code._kernel(mat_dev, dev, donate=donate)
            outs.append(out)
            t0 = self._mark("matmul", t0)
            if len(outs) > 1:
                # pull tile t-1's result under tile t's compute: the
                # device→host copy of an already-finished tile overlaps
                # the in-flight matmul instead of queueing serially
                # behind the final drain.  The pull still counts as
                # `unpack` — overlapped or not, it is device→host
                # reassembly time (keeps the stage histogram honest).
                # cesslint: allow[host-sync] pulls the PREVIOUS tile,
                # already computed, while tile t is still in flight
                pulled.append(np.asarray(outs[-2]))
                outs[-2] = None
                t0 = self._mark("unpack", t0)
        jax.block_until_ready(outs[-1])
        t0 = self._mark("dispatch_wait", t0)
        pulled.append(np.asarray(outs[-1]))
        res = np.concatenate(pulled, axis=1)[:, :n]
        self._mark("unpack", t0)
        self._account(data.nbytes, t_start)
        return res

    # -- segment-axis stream -------------------------------------------

    def _patterns(self, b: int) -> list[tuple[int, ...]]:
        code = self.code
        if not _is_per_segment(self.present):
            mask = check_present(self.present, code.k, code.m)
            return [mask] * b
        pats = [
            check_present(p, code.k, code.m) for p in self.present
        ]
        if len(pats) != b:
            raise ValueError(
                f"{len(pats)} survivor lists for {b} segments"
            )
        return pats

    def _stream_slabs(self, mat: np.ndarray, batch: np.ndarray, out, idx):
        """Gather one group's segments out of `batch`, dispatch them in
        fixed-size slabs, and scatter results into `out` rows `idx`."""
        code = self.code
        mat_dev = code._mat_dev(mat)
        slab = self.slab
        t0 = _time.perf_counter()
        batch = batch[idx, : code.k]  # group gather = host pack work
        b = batch.shape[0]
        outs = []
        pulled = []
        for off in range(0, b, slab):
            chunk = batch[off : off + slab]
            if chunk.shape[0] != slab:  # padded tail slab: one shape
                padded = np.zeros(
                    (slab,) + chunk.shape[1:], dtype=np.uint8
                )
                padded[: chunk.shape[0]] = chunk
                chunk = padded
            dev = jax.device_put(np.ascontiguousarray(chunk))
            t0 = self._mark("pack", t0)
            outs.append(
                code._batch_kernel(
                    mat_dev, dev, mesh=self.mesh,
                    donate=self.present is not None,
                )
            )
            t0 = self._mark("matmul", t0)
            if len(outs) > 1:
                # pull slab t-1's result under slab t's compute (see
                # RSStream.run): overlapped device→host copies still
                # accrue to `unpack`
                # cesslint: allow[host-sync] pulls the PREVIOUS slab,
                # already computed, while slab t is still in flight
                pulled.append(np.asarray(outs[-2]))
                outs[-2] = None
                t0 = self._mark("unpack", t0)
        jax.block_until_ready(outs[-1])
        t0 = self._mark("dispatch_wait", t0)
        pulled.append(np.asarray(outs[-1]))
        got = np.concatenate(pulled, axis=0)[:b]
        out[idx] = got
        self._mark("unpack", t0)

    def run_batch(self, batch: np.ndarray) -> np.ndarray:
        """(B, rows, n) host segments -> (B, out_rows, n) host uint8.

        Encode (`present=None`): rows = k, out_rows = m.  Reconstruct:
        per-segment survivor rows; segments sharing a survivor mask are
        grouped into one batched matmul stream each (grouped
        per-pattern recovery), every dispatch a fixed (slab, k, n)
        shape so ALL groups share one executable.
        """
        code = self.code
        t_start = _time.perf_counter()
        _check_shards(batch, code.k, batched=True)
        batch = np.asarray(batch, dtype=np.uint8)
        b, _, n = batch.shape
        if self.present is None:
            if batch.shape[1] != code.k:
                raise ValueError(
                    f"encode stream needs exactly {code.k} data rows, "
                    f"got {batch.shape[1]}"
                )
            out = np.empty((b, code.m, n), dtype=np.uint8)
            self._stream_slabs(code._parity, batch, out, slice(None))
            self._account(batch.nbytes, t_start)
            return out
        pats = self._patterns(b)
        out = np.empty((b, code.k, n), dtype=np.uint8)
        groups: dict[tuple[int, ...], list[int]] = {}
        for i, p in enumerate(pats):
            groups.setdefault(p, []).append(i)
        for mask, idx in groups.items():
            inv = _inv_cached(code.k, code.m, mask)
            # cesslint: allow[host-sync] np.asarray on a host-side
            # python index list (group gather rows), not a device value
            self._stream_slabs(inv, batch, out, np.asarray(idx))
        self._account(batch.nbytes, t_start)
        return out


# Protocol geometry (reference: primitives/common/src/lib.rs:60-62 — 16 MiB
# segments, 8 MiB fragments, i.e. k=2 data + m=1 parity).
SEGMENT_K = 2
SEGMENT_M = 1


def segment_code(path: str = "auto", tile: int | None = None) -> RSCode:
    return RSCode(SEGMENT_K, SEGMENT_M, path=path, tile=tile)
