"""BLS12-381: fields, curves, pairing, signatures — host reference.

This is the framework's bit-exactness anchor for everything BLS: the batched
TPU kernels (ops/g1.py, ops/fr.py) and the PoDR2 verifier (ops/podr2.py) are
tested against this module, which re-expresses the capability of the
reference's `verify-bls-signatures` crate (reference:
utils/verify-bls-signatures/src/lib.rs — IC-style BLS: 48-byte G1
signatures, 96-byte G2 public keys, pairing check via multi-Miller-loop +
final exponentiation, lib.rs:85-100) and of `cp-enclave-verify`'s
`verify_bls` (reference: primitives/enclave-verify/src/lib.rs:230-235).

Everything here is standard, publicly specified mathematics implemented from
the curve definition:

  parameter     x  = -0xd201000000010000
  base field    p  = (x-1)^2 (x^4 - x^2 + 1)/3 + x      (381 bits)
  scalar field  r  = x^4 - x^2 + 1                      (255 bits)
  E : y^2 = x^3 + 4    over Fp        (G1)
  E': y^2 = x^3 + 4(u+1) over Fp2     (G2, M-twist)
  tower: Fp2 = Fp[u]/(u^2+1); Fp6 = Fp2[v]/(v^3-(u+1)); Fp12 = Fp6[w]/(w^2-v)

The module self-checks p and r against the x-parameter identities at import.

Hash-to-G1 is the full RFC 9380 suite `BLS_SIG_BLS12381G1_XMD:SHA-256_
SSWU_RO_NUL_`: expand_message_xmd, simplified SWU onto the 11-isogenous
curve, the 11-isogeny back to E (coefficients DERIVED by
tools/derive_sswu.py, carried in ops/_sswu_g1.py), and h_eff cofactor
clearing.  Interop with the reference's IC vectors is asserted verbatim in
tests/test_bls12_381.py::TestReferenceKATs.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

# ---------------------------------------------------------------- parameters

BLS_X = 0xD201000000010000  # |x|; the BLS parameter itself is -BLS_X
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Self-check the remembered constants against the defining identities.
_x = -BLS_X
assert R == _x**4 - _x**2 + 1, "r must equal x^4 - x^2 + 1"
assert P == (_x - 1) ** 2 * (_x**4 - _x**2 + 1) // 3 + _x, "p identity"
assert P % 4 == 3

# Effective G1 cofactor for hash-to-curve: h_eff = 1 - z (RFC 9380
# §8.8.1).  The FULL cofactor is (z-1)^2/3; both clear the cofactor but
# differ by a scalar on the r-torsion — the IC vectors pin h_eff.
H_EFF_G1 = 1 - (-BLS_X)  # 1 − z with z = −BLS_X
assert H_EFF_G1 == 0xD201000000010001
DST_G1 = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_"


# ---------------------------------------------------------------- Fp

def fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> int | None:
    """p ≡ 3 (mod 4) ⇒ sqrt = a^((p+1)/4) when it exists."""
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a % P else None


# ---------------------------------------------------------------- Fp2

class Fq2:
    """c0 + c1·u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int = 0) -> None:
        self.c0 = c0 % P
        self.c1 = c1 % P

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o) -> "Fq2":
        if isinstance(o, int):
            return Fq2(self.c0 * o, self.c1 * o)
        # Karatsuba: (a0+a1u)(b0+b1u) = a0b0 - a1b1 + ((a0+a1)(b0+b1)-a0b0-a1b1)u
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fq2(t0 - t1, t2 - t0 - t1)

    __rmul__ = __mul__

    def square(self) -> "Fq2":
        # (a0+a1u)^2 = (a0+a1)(a0-a1) + 2a0a1 u
        t = self.c0 * self.c1
        return Fq2((self.c0 + self.c1) * (self.c0 - self.c1), 2 * t)

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def inv(self) -> "Fq2":
        # 1/(a0+a1u) = (a0-a1u)/(a0^2+a1^2)
        norm = self.c0 * self.c0 + self.c1 * self.c1
        ninv = fp_inv(norm)
        return Fq2(self.c0 * ninv, -self.c1 * ninv)

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def pow(self, e: int) -> "Fq2":
        result, base = FQ2_ONE, self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def sqrt(self) -> "Fq2 | None":
        """Tonelli–Shanks in Fp2 (q = p^2, q-1 = 2^s·t)."""
        if self.is_zero():
            return FQ2_ZERO
        q1 = P * P - 1
        s = (q1 & -q1).bit_length() - 1
        t = q1 >> s
        # Deterministic non-residue search.
        z = None
        for cand in _FQ2_NONRESIDUE_CANDIDATES:
            if cand.pow(q1 // 2) == FQ2_MINUS_ONE:
                z = cand
                break
        assert z is not None
        m = s
        c = z.pow(t)
        r_ = self.pow((t + 1) // 2)
        t_ = self.pow(t)
        while t_ != FQ2_ONE:
            # find least i with t^(2^i) == 1
            i, t2 = 0, t_
            while t2 != FQ2_ONE:
                t2 = t2.square()
                i += 1
                if i == m:
                    return None  # not a square
            b = c
            for _ in range(m - i - 1):
                b = b.square()
            m = i
            c = b.square()
            t_ = t_ * c
            r_ = r_ * b
        return r_ if r_.square() == self else None

    def sgn0(self) -> int:
        """RFC 9380 sign: lexicographic over (c0, c1)."""
        if self.c0 != 0:
            return self.c0 & 1
        return self.c1 & 1

    def __repr__(self) -> str:  # pragma: no cover
        return f"Fq2({hex(self.c0)}, {hex(self.c1)})"


FQ2_ZERO = Fq2(0)
FQ2_ONE = Fq2(1)
FQ2_MINUS_ONE = Fq2(P - 1)
XI = Fq2(1, 1)  # ξ = u + 1, the sextic-twist constant
_FQ2_NONRESIDUE_CANDIDATES = [Fq2(1, 1), Fq2(2, 1), Fq2(1, 2), Fq2(3, 1), Fq2(2, 3)]


# ---------------------------------------------------------------- Fp6 / Fp12

class Fq6:
    """c0 + c1·v + c2·v^2 with v^3 = ξ."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2) -> None:
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __eq__(self, o) -> bool:
        return (
            isinstance(o, Fq6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o) -> "Fq6":
        if isinstance(o, (int, Fq2)):
            return Fq6(self.c0 * o, self.c1 * o, self.c2 * o)
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        return Fq6(
            t0 + ((a1 + a2) * (b1 + b2) - t1 - t2) * XI,
            (a0 + a1) * (b0 + b1) - t0 - t1 + t2 * XI,
            (a0 + a2) * (b0 + b2) - t0 - t2 + t1,
        )

    __rmul__ = __mul__

    def square(self) -> "Fq6":
        return self * self

    def mul_by_v(self) -> "Fq6":
        return Fq6(self.c2 * XI, self.c0, self.c1)

    def inv(self) -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - a1 * a2 * XI
        t1 = a2.square() * XI - a0 * a1
        t2 = a1.square() - a0 * a2
        norm = a0 * t0 + (a2 * t1 + a1 * t2) * XI
        ninv = norm.inv()
        return Fq6(t0 * ninv, t1 * ninv, t2 * ninv)

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()


FQ6_ZERO = Fq6(FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = Fq6(FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


class Fq12:
    """c0 + c1·w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6) -> None:
        self.c0, self.c1 = c0, c1

    @classmethod
    def from_fq2(cls, a: Fq2) -> "Fq12":
        return cls(Fq6(a, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)

    @classmethod
    def from_int(cls, a: int) -> "Fq12":
        return cls.from_fq2(Fq2(a))

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o) -> "Fq12":
        if isinstance(o, (int, Fq2)):
            return Fq12(self.c0 * o, self.c1 * o)
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fq12(t0 + t1.mul_by_v(), t2 - t0 - t1)

    __rmul__ = __mul__

    def square(self) -> "Fq12":
        # complex squaring: 2 Fq6 muls instead of the generic mul's 3 —
        # the final exponentiation is square-dominated, so this is the
        # single highest-leverage pairing op
        t0 = self.c0 * self.c1
        return Fq12(
            (self.c0 + self.c1) * (self.c0 + self.c1.mul_by_v())
            - t0 - t0.mul_by_v(),
            t0 + t0,
        )

    def conjugate(self) -> "Fq12":
        """The p^6-Frobenius: c0 - c1·w."""
        return Fq12(self.c0, -self.c1)

    def inv(self) -> "Fq12":
        norm = self.c0.square() - self.c1.square().mul_by_v()
        ninv = norm.inv()
        return Fq12(self.c0 * ninv, -(self.c1 * ninv))

    def pow(self, e: int) -> "Fq12":
        """4-bit fixed-window exponentiation: the ~2000-bit final-exp
        exponent costs ~n squares + n/4 muls instead of n + n/2."""
        if e < 0:
            return self.inv().pow(-e)
        if e == 0:
            return FQ12_ONE
        table = [FQ12_ONE, self]
        for _ in range(14):
            table.append(table[-1] * self)
        digits = []
        while e:
            digits.append(e & 15)
            e >>= 4
        result = table[digits[-1]]
        for d in reversed(digits[:-1]):
            result = result.square().square().square().square()
            if d:
                result = result * table[d]
        return result

    def is_one(self) -> bool:
        return self == FQ12_ONE


FQ12_ZERO = Fq12(FQ6_ZERO, FQ6_ZERO)
FQ12_ONE = Fq12(FQ6_ONE, FQ6_ZERO)
# w as an Fq12 element: coefficient 1 on the w term.
FQ12_W = Fq12(FQ6_ZERO, FQ6_ONE)


# ---------------------------------------------------------------- curves

def _jac_double_fp(x: int, y: int, z: int) -> tuple[int, int, int]:
    """Jacobian doubling on y^2 = x^3 + b over Fp (a = 0)."""
    if z == 0 or y == 0:
        return 0, 1, 0
    a = x * x % P
    b = y * y % P
    c = b * b % P
    t = x + b
    d = 2 * (t * t - a - c) % P
    e = 3 * a % P
    f = e * e % P
    x3 = (f - 2 * d) % P
    y3 = (e * (d - x3) - 8 * c) % P
    z3 = 2 * y * z % P
    return x3, y3, z3


def _jac_add_fp(
    x1: int, y1: int, z1: int, x2: int, y2: int, z2: int
) -> tuple[int, int, int]:
    if z1 == 0:
        return x2, y2, z2
    if z2 == 0:
        return x1, y1, z1
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return 0, 1, 0
        return _jac_double_fp(x1, y1, z1)
    h = (u2 - u1) % P
    i = (2 * h) ** 2 % P
    j = h * i % P
    r_ = 2 * (s2 - s1) % P
    v = u1 * i % P
    x3 = (r_ * r_ - j - 2 * v) % P
    y3 = (r_ * (v - x3) - 2 * s1 * j) % P
    z3 = 2 * z1 * z2 % P * h % P
    return x3, y3, z3

class G1Point:
    """Affine point on E: y^2 = x^3 + 4 (None coords = infinity)."""

    __slots__ = ("x", "y")
    B = 4

    def __init__(self, x: int | None, y: int | None) -> None:
        self.x, self.y = x, y

    @classmethod
    def infinity(cls) -> "G1Point":
        return cls(None, None)

    def is_infinity(self) -> bool:
        return self.x is None

    def __eq__(self, o) -> bool:
        return isinstance(o, G1Point) and self.x == o.x and self.y == o.y

    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        return (self.y * self.y - self.x**3 - self.B) % P == 0

    def __neg__(self) -> "G1Point":
        if self.is_infinity():
            return self
        return G1Point(self.x, (-self.y) % P)

    def __add__(self, o: "G1Point") -> "G1Point":
        if self.is_infinity():
            return o
        if o.is_infinity():
            return self
        if self.x == o.x:
            if (self.y + o.y) % P == 0:
                return G1Point.infinity()
            # doubling
            lam = 3 * self.x * self.x * fp_inv(2 * self.y) % P
        else:
            lam = (o.y - self.y) * fp_inv((o.x - self.x) % P) % P
        x3 = (lam * lam - self.x - o.x) % P
        y3 = (lam * (self.x - x3) - self.y) % P
        return G1Point(x3, y3)

    def mul(self, k: int) -> "G1Point":
        """Scalar mult in Jacobian coordinates (one inversion total)."""
        k %= R
        return self._mul_raw(k)

    def _mul_raw(self, k: int) -> "G1Point":
        if k == 0 or self.is_infinity():
            return G1Point.infinity()
        # Jacobian (X, Y, Z): x = X/Z^2, y = Y/Z^3; a = 0 curve.
        rx, ry, rz = 0, 1, 0  # infinity
        bx, by, bz = self.x, self.y, 1
        while k:
            if k & 1:
                rx, ry, rz = _jac_add_fp(rx, ry, rz, bx, by, bz)
            bx, by, bz = _jac_double_fp(bx, by, bz)
            k >>= 1
        if rz == 0:
            return G1Point.infinity()
        zinv = fp_inv(rz)
        z2 = zinv * zinv % P
        return G1Point(rx * z2 % P, ry * z2 % P * zinv % P)

    def in_subgroup(self) -> bool:
        return self.is_on_curve() and self._mul_raw(R).is_infinity()

    # -- zkcrypto-compatible compressed serialization (48 bytes) --------

    def to_bytes(self) -> bytes:
        if self.is_infinity():
            out = bytearray(48)
            out[0] = 0xC0
            return bytes(out)
        out = bytearray(self.x.to_bytes(48, "big"))
        out[0] |= 0x80  # compression flag
        if self.y > P - self.y:  # lexicographically largest root
            out[0] |= 0x20
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "G1Point":
        if len(data) != 48:
            raise ValueError("G1 compressed point must be 48 bytes")
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("uncompressed G1 encoding unsupported")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("invalid infinity encoding")
            return cls.infinity()
        x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
        if x >= P:
            raise ValueError("x out of range")
        y = fp_sqrt((x**3 + cls.B) % P)
        if y is None:
            raise ValueError("point not on curve")
        y_is_large = y > P - y
        if bool(flags & 0x20) != y_is_large:
            y = P - y
        point = cls(x, y)
        if not point.in_subgroup():
            raise ValueError("point not in G1 subgroup")
        return point

    def __repr__(self) -> str:  # pragma: no cover
        return "G1(inf)" if self.is_infinity() else f"G1({hex(self.x)},..)"


def g1_decompress_unchecked(data: bytes) -> G1Point:
    """Compressed G1 → point with encoding + on-curve validation but the
    subgroup membership test DEFERRED (the fused verify pipeline runs it
    as a batched device [r]-chain — ops/glv.py subgroup_mask — instead
    of a per-point host ladder).  Raises ValueError for exactly the
    encodings G1Point.from_bytes rejects before its subgroup test."""
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding unsupported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("invalid infinity encoding")
        return G1Point.infinity()
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("x out of range")
    y = fp_sqrt((x**3 + G1Point.B) % P)
    if y is None:
        raise ValueError("point not on curve")
    if bool(flags & 0x20) != (y > P - y):
        y = P - y
    return G1Point(x, y)


# ---------------------------------------------------- batched decompression
#
# One G1Point.from_bytes costs one fp_sqrt — a (p+1)/4 exponentiation,
# ~0.3 ms per σ — plus a ~3 ms host Python subgroup ladder.  At
# batch-verify scale that is the classic per-proof host residue.  Two
# facts shape the batch form:
#
#  * Square roots do not batch: Montgomery's trick turns N inversions
#    into one because inv(a_i) = inv(Πa)·Π_{j≠i}a_j, but the root of a
#    product gives only the PRODUCT of the roots — there is no
#    per-element relation to unwind, so each lane pays its own
#    exponentiation.  CPython's pow() (C sliding-window) was measured
#    5× faster per lane than a shared square-and-multiply chain over
#    vectorised numpy uint64 limbs (the ops/g1.py design scaled to
#    host), so the chain stays in C and the batch amortises the
#    Python-level validation instead.
#  * The subgroup ladder is the part worth moving: check_subgroup=False
#    defers it so callers run ONE batched device [r]-chain
#    (ops/glv.py subgroup_mask) over the whole batch — bit-identical
#    rejection, none of the per-point host milliseconds.
#
# Bit-identity with the scalar path (fp_sqrt / from_bytes /
# g1_decompress_unchecked), including the rejection set, is asserted in
# tests/test_proof_hotpath.py.


def fp_sqrt_batch(values: list[int]) -> list[int | None]:
    """Batch fp_sqrt — literally a loop over the scalar helper (see the
    module comment above: per-lane C pow() is the fastest chain), kept
    as the batch seam so a future backend that CAN amortise roots slots
    in without touching callers."""
    return [fp_sqrt(v % P) for v in values]


def g1_decompress_batch(
    blobs: list[bytes], check_subgroup: bool = True
) -> list[G1Point]:
    """Batched compressed-G1 decompression, bit-identical to a loop of
    G1Point.from_bytes (check_subgroup=True) or g1_decompress_unchecked
    (check_subgroup=False): the same ValueError rejection set — bad
    length, uncompressed/invalid-infinity flags, x ≥ p, non-residue x³+4,
    and (when checked) non-subgroup points — and the same points out,
    including the point at infinity and both sign flags.  Raises on the
    FIRST invalid item of each validation phase; callers that need
    per-item verdicts bisect, exactly as they do over the scalar path.

    The square roots stay per-lane C pow() (fp_sqrt_batch — see the
    module comment for why they don't batch); what the batch form
    amortises is the Python-level validation and, via
    check_subgroup=False, the subgroup ladder.  check_subgroup=False
    is the fast path for verifiers that defer the subgroup test to the
    batched device [r]-chain (ops/glv.py subgroup_mask)."""
    n = len(blobs)
    out: list[G1Point | None] = [None] * n
    lanes: list[int] = []
    xs: list[int] = []
    large: list[bool] = []
    for k, data in enumerate(blobs):
        if len(data) != 48:
            raise ValueError("G1 compressed point must be 48 bytes")
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("uncompressed G1 encoding unsupported")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("invalid infinity encoding")
            out[k] = G1Point.infinity()
            continue
        x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
        if x >= P:
            raise ValueError("x out of range")
        lanes.append(k)
        xs.append(x)
        large.append(bool(flags & 0x20))
    if lanes:
        roots = fp_sqrt_batch([(x * x % P * x + G1Point.B) % P for x in xs])
        for k, x, y, lg in zip(lanes, xs, roots, large):
            if y is None:
                raise ValueError("point not on curve")
            if lg != (y > P - y):
                y = P - y
            out[k] = G1Point(x, y)
        if check_subgroup:
            for k in lanes:
                if not out[k].in_subgroup():
                    raise ValueError("point not in G1 subgroup")
    return out


def _jac_double_fq2(x: Fq2, y: Fq2, z: Fq2) -> tuple[Fq2, Fq2, Fq2]:
    if z.is_zero() or y.is_zero():
        return FQ2_ZERO, FQ2_ONE, FQ2_ZERO
    a = x.square()
    b = y.square()
    c = b.square()
    d = ((x + b).square() - a - c) * 2
    e = a * 3
    f = e.square()
    x3 = f - d * 2
    y3 = e * (d - x3) - c * 8
    z3 = y * z * 2
    return x3, y3, z3


def _jac_add_fq2(
    x1: Fq2, y1: Fq2, z1: Fq2, x2: Fq2, y2: Fq2, z2: Fq2
) -> tuple[Fq2, Fq2, Fq2]:
    if z1.is_zero():
        return x2, y2, z2
    if z2.is_zero():
        return x1, y1, z1
    z1z1 = z1.square()
    z2z2 = z2.square()
    u1 = x1 * z2z2
    u2 = x2 * z1z1
    s1 = y1 * z2 * z2z2
    s2 = y2 * z1 * z1z1
    if u1 == u2:
        if s1 != s2:
            return FQ2_ZERO, FQ2_ONE, FQ2_ZERO
        return _jac_double_fq2(x1, y1, z1)
    h = u2 - u1
    i = (h * 2).square()
    j = h * i
    r_ = (s2 - s1) * 2
    v = u1 * i
    x3 = r_.square() - j - v * 2
    y3 = r_ * (v - x3) - s1 * j * 2
    z3 = z1 * z2 * h * 2
    return x3, y3, z3


class G2Point:
    """Affine point on E': y^2 = x^3 + 4(u+1) over Fp2."""

    __slots__ = ("x", "y")
    B = Fq2(4, 4)

    def __init__(self, x: Fq2 | None, y: Fq2 | None) -> None:
        self.x, self.y = x, y

    @classmethod
    def infinity(cls) -> "G2Point":
        return cls(None, None)

    def is_infinity(self) -> bool:
        return self.x is None

    def __eq__(self, o) -> bool:
        return isinstance(o, G2Point) and self.x == o.x and self.y == o.y

    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        return self.y.square() == self.x.square() * self.x + self.B

    def __neg__(self) -> "G2Point":
        if self.is_infinity():
            return self
        return G2Point(self.x, -self.y)

    def __add__(self, o: "G2Point") -> "G2Point":
        if self.is_infinity():
            return o
        if o.is_infinity():
            return self
        if self.x == o.x:
            if (self.y + o.y).is_zero():
                return G2Point.infinity()
            lam = (self.x.square() * 3) * (self.y * 2).inv()
        else:
            lam = (o.y - self.y) * (o.x - self.x).inv()
        x3 = lam.square() - self.x - o.x
        y3 = lam * (self.x - x3) - self.y
        return G2Point(x3, y3)

    def mul(self, k: int) -> "G2Point":
        """Scalar mult in Jacobian coordinates over Fp2."""
        k %= R
        return self._mul_raw(k)

    def _mul_raw(self, k: int) -> "G2Point":
        if k == 0 or self.is_infinity():
            return G2Point.infinity()
        rx, ry, rz = FQ2_ZERO, FQ2_ONE, FQ2_ZERO
        bx, by, bz = self.x, self.y, FQ2_ONE
        while k:
            if k & 1:
                rx, ry, rz = _jac_add_fq2(rx, ry, rz, bx, by, bz)
            bx, by, bz = _jac_double_fq2(bx, by, bz)
            k >>= 1
        if rz.is_zero():
            return G2Point.infinity()
        zinv = rz.inv()
        z2 = zinv.square()
        return G2Point(rx * z2, ry * z2 * zinv)

    def in_subgroup(self) -> bool:
        return self.is_on_curve() and self._mul_raw(R).is_infinity()

    # -- compressed serialization (96 bytes, c1 first) -------------------

    def to_bytes(self) -> bytes:
        if self.is_infinity():
            out = bytearray(96)
            out[0] = 0xC0
            return bytes(out)
        out = bytearray(
            self.x.c1.to_bytes(48, "big") + self.x.c0.to_bytes(48, "big")
        )
        out[0] |= 0x80
        neg = -self.y
        # lexicographic order over (c1, c0)
        if (self.y.c1, self.y.c0) > (neg.c1, neg.c0):
            out[0] |= 0x20
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "G2Point":
        if len(data) != 96:
            raise ValueError("G2 compressed point must be 96 bytes")
        flags = data[0]
        if not flags & 0x80:
            raise ValueError("uncompressed G2 encoding unsupported")
        if flags & 0x40:
            if any(data[1:]) or flags & 0x3F:
                raise ValueError("invalid infinity encoding")
            return cls.infinity()
        c1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
        c0 = int.from_bytes(data[48:96], "big")
        if c0 >= P or c1 >= P:
            raise ValueError("x out of range")
        x = Fq2(c0, c1)
        y = (x.square() * x + cls.B).sqrt()
        if y is None:
            raise ValueError("point not on curve")
        neg = -y
        y_is_large = (y.c1, y.c0) > (neg.c1, neg.c0)
        if bool(flags & 0x20) != y_is_large:
            y = neg
        point = cls(x, y)
        if not point.in_subgroup():
            raise ValueError("point not in G2 subgroup")
        return point


G1_GENERATOR = G1Point(
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GENERATOR = G2Point(
    Fq2(
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    Fq2(
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)
assert G1_GENERATOR.is_on_curve()
assert G2_GENERATOR.is_on_curve()


# ---------------------------------------------------------------- pairing

def _untwist(q: G2Point) -> tuple[Fq12, Fq12]:
    """E'(Fp2) → E(Fp12): (x', y') → (x'/w^2, y'/w^3)."""
    w2_inv = (FQ12_W * FQ12_W).inv()
    w3_inv = (FQ12_W * FQ12_W * FQ12_W).inv()
    return (Fq12.from_fq2(q.x) * w2_inv, Fq12.from_fq2(q.y) * w3_inv)


def _line_coeff(t, q):
    """One chord-and-tangent step of the affine Miller loop, Q-side
    only: the slope and chord point involve no G1 input, so they are
    precomputable per Q.  Returns ((mode, lam, tx, ty), t+q) where
    mode 0 = sloped line (evaluate -((px-tx)·lam - (py-ty))) and
    mode 1 = vertical (evaluate px - tx)."""
    tx, ty = t
    qx, qy = q
    if tx == qx and ty == qy:
        lam = tx.square() * 3 * (ty * 2).inv()
    elif tx == qx:
        return (1, None, tx, ty), (None, None)
    else:
        lam = (qy - ty) * (qx - tx).inv()
    x3 = lam.square() - tx - qx
    y3 = lam * (tx - x3) - ty
    return (0, lam, tx, ty), (x3, y3)


def _q_coeffs(q: G2Point) -> list:
    """Per-Q Miller-loop line coefficients.  Every slope/inversion in
    the loop depends only on Q, so for recurring Q's (the G2 generator
    in every signature check, each validator's registered key) the
    whole inversion chain is computed once and the per-pairing work is
    evaluation only."""
    qt = _untwist(q)
    coeffs = []
    t = qt
    for bit in bin(BLS_X)[3:]:
        c, t = _line_coeff(t, t)
        coeffs.append(c)
        if bit == "1":
            c, t = _line_coeff(t, qt)
            coeffs.append(c)
    return coeffs


# LRU keyed by the affine G2 coordinates.  Verifies run concurrently
# from RPC/gossip/import threads, so all cache access is under a lock;
# recency eviction keeps hot keys (validators, the G2 generator) cached
# even when the account population exceeds the capacity.
_Q_COEFF_CACHE: "OrderedDict" = OrderedDict()
_Q_COEFF_CACHE_MAX = 256
_Q_COEFF_LOCK = threading.Lock()


def _q_coeffs_cached(q: G2Point) -> list:
    key = (q.x.c0, q.x.c1, q.y.c0, q.y.c1)
    with _Q_COEFF_LOCK:
        hit = _Q_COEFF_CACHE.get(key)
        if hit is not None:
            _Q_COEFF_CACHE.move_to_end(key)
            return hit
    coeffs = _q_coeffs(q)  # expensive inversion chain: outside the lock
    with _Q_COEFF_LOCK:
        _Q_COEFF_CACHE[key] = coeffs
        _Q_COEFF_CACHE.move_to_end(key)
        while len(_Q_COEFF_CACHE) > _Q_COEFF_CACHE_MAX:
            _Q_COEFF_CACHE.popitem(last=False)
    return coeffs


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    """Miller loop of the optimal ate pairing (negative-x BLS12:
    conjugate at the end) — reference capability:
    utils/verify-bls-signatures/src/lib.rs:85-100.  Q-side line
    coefficients come from the per-Q cache; the per-call work is the
    G1-side evaluation and the f accumulation."""
    if p.is_infinity() or q.is_infinity():
        return FQ12_ONE
    coeffs = _q_coeffs_cached(q)
    px, py = Fq12.from_int(p.x), Fq12.from_int(p.y)

    def line_at_p(c):
        mode, lam, tx, ty = c
        # vertical line (mode) vs sloped tangent/chord through T
        return px - tx if mode else -((px - tx) * lam - (py - ty))

    f = FQ12_ONE
    i = 0
    for bit in bin(BLS_X)[3:]:
        f = f.square() * line_at_p(coeffs[i])
        i += 1
        if bit == "1":
            f = f * line_at_p(coeffs[i])
            i += 1
    # x < 0 ⇒ conjugate (Frobenius^6)
    return f.conjugate()


_FINAL_EXP = (P**12 - 1) // R


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p^12-1)/r).  Easy part via conjugation/inversion, remainder by
    square-and-multiply (correctness-first; the fixed exponent makes this
    replay-safe)."""
    # easy part: f^(p^6 - 1) = conj(f) * f^-1 — cheapens the remaining pow
    f = f.conjugate() * f.inv()
    # remaining exponent: (p^6+1)(p^4-p^2+1)/r … folded into one pow of the
    # quotient of what's left.
    return f.pow(_FINAL_EXP // (P**6 - 1))


def pairing(p: G1Point, q: G2Point) -> Fq12:
    return final_exponentiation(miller_loop(p, q))


def multi_pairing(pairs: list[tuple[G1Point, G2Point]]) -> Fq12:
    """Π e(P_i, Q_i) with a single final exponentiation (the
    multi_miller_loop pattern, reference lib.rs:85-100)."""
    f = FQ12_ONE
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f)


def pairing_check(pairs: list[tuple[G1Point, G2Point]]) -> bool:
    """Π e(P_i, Q_i) == 1 — the form every verifier reduces to."""
    return multi_pairing(pairs).is_one()


# ---------------------------------------------------------------- hash to G1

def expand_message_xmd(msg: bytes, dst: bytes, out_len: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256 (exact)."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    b_in_bytes = 32
    r_in_bytes = 64
    ell = -(-out_len // b_in_bytes)
    if ell > 255:
        raise ValueError("expand_message_xmd: output too long")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = bytes(r_in_bytes)
    l_i_b_str = out_len.to_bytes(2, "big")
    b0 = hashlib.sha256(
        z_pad + msg + l_i_b_str + b"\x00" + dst_prime
    ).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        blocks.append(
            hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest()
        )
    return b"".join(blocks)[:out_len]


def hash_to_field_fp(msg: bytes, dst: bytes, count: int) -> list[int]:
    """RFC 9380 §5.2 hash_to_field for Fp (m=1, L=64)."""
    length = 64
    uniform = expand_message_xmd(msg, dst, count * length)
    return [
        int.from_bytes(uniform[i * length : (i + 1) * length], "big") % P
        for i in range(count)
    ]


def _sswu_consts():
    from . import _sswu_g1

    return _sswu_g1


def map_to_curve_g1(u: int) -> G1Point:
    """RFC 9380 §6.6.2/§6.6.3 map Fp → E: simplified SWU onto the
    11-isogenous curve E' (A', B', Z = 11), then the 11-isogeny to E.

    The isogeny coefficients are DERIVED by tools/derive_sswu.py
    (division polynomial → rational kernel → Vélu → codomain scaling)
    and pinned to the IC vectors mirrored from the reference
    (utils/verify-bls-signatures/tests/tests.rs:19-127)."""
    c = _sswu_consts()
    A, B, Z = c.A_PRIME, c.B_PRIME, c.Z_SSWU
    u %= P
    tv = Z * u % P * u % P
    tv2 = (tv * tv + tv) % P
    if tv2 == 0:
        x1 = B * pow(Z * A % P, P - 2, P) % P
    else:
        x1 = (-B) % P * pow(A, P - 2, P) % P * (1 + pow(tv2, P - 2, P)) % P
    gx1 = (x1 * x1 % P * x1 + A * x1 + B) % P
    y = fp_sqrt(gx1)
    if y is not None:
        x = x1
    else:
        x = tv * x1 % P
        gx2 = (x * x % P * x + A * x + B) % P
        y = fp_sqrt(gx2)
        assert y is not None, "SSWU: neither candidate is square"
    if (y & 1) != (u & 1):  # sgn0 alignment
        y = P - y
    # 11-isogeny E' → E (x' = XN/XD, y' = y·YN/YD; poles → infinity)
    xd = _poly_eval(c.X_DEN, x)
    if xd == 0:
        return G1Point.infinity()
    X = _poly_eval(c.X_NUM, x) * pow(xd, P - 2, P) % P
    Y = y * _poly_eval(c.Y_NUM, x) % P * pow(
        _poly_eval(c.Y_DEN, x), P - 2, P
    ) % P
    return G1Point(X, Y)


def _poly_eval(coeffs: list[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % P
    return acc


def clear_cofactor_g1(p: G1Point) -> G1Point:
    """Multiply by the effective cofactor h_eff = 1 − z (RFC 9380 §8.8.1)
    — NOT the full cofactor (z−1)²/3; they differ by a scalar on the
    r-torsion and the IC vectors pin this one.  Via _mul_raw, which does
    not reduce the scalar mod r."""
    return p._mul_raw(H_EFF_G1)


def hash_to_g1(msg: bytes, dst: bytes = DST_G1) -> G1Point:
    """hash_to_curve for G1 (RFC 9380 hash_to_curve, SSWU route): two
    field elements, map both through SSWU + isogeny, add, clear
    cofactor.  With dst=IC_DST this is the exact suite the reference
    verifies (BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_)."""
    u0, u1 = hash_to_field_fp(msg, dst, 2)
    q = map_to_curve_g1(u0) + map_to_curve_g1(u1)
    return clear_cofactor_g1(q)


# ---------------------------------------------------------------- signatures

def keygen(seed: bytes) -> int:
    """Deterministic secret key from seed (nonzero scalar)."""
    sk = int.from_bytes(
        hashlib.blake2b(b"cess-bls-keygen" + seed, digest_size=48).digest(), "big"
    ) % R
    return sk or 1


def sk_to_pk(sk: int) -> bytes:
    return G2_GENERATOR.mul(sk).to_bytes()


def sign(sk: int, msg: bytes) -> bytes:
    """48-byte G1 signature (reference: verify-bls-signatures sign path,
    lib.rs:176-237)."""
    return hash_to_g1(msg).mul(sk).to_bytes()


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """e(sig, g2) == e(H(msg), pk), computed as
    e(sig, -g2)·e(H(msg), pk) == 1 (reference: lib.rs:85-100)."""
    try:
        sig_point = G1Point.from_bytes(sig)
        pk_point = G2Point.from_bytes(pk)
    except ValueError:
        return False
    h = hash_to_g1(msg)
    return pairing_check([(sig_point, -G2_GENERATOR), (h, pk_point)])


def verify_bls_signature(sig: bytes, msg: bytes, key: bytes) -> bool:
    """IC-compatible entry point with the reference crate's argument
    order (utils/verify-bls-signatures/src/lib.rs:85-100): 48-byte
    compressed G1 signature, arbitrary message, 96-byte compressed G2
    public key.  Interop is pinned by the reference KATs
    (tests/tests.rs:19-127 → tests/test_bls12_381.py)."""
    return verify(key, msg, sig)
