"""Generic fixed-modulus big-integer arithmetic on TPU (base-128 limbs).

Generalizes the Fr machinery (ops/fr.py) to an arbitrary odd modulus fixed
per batch — the RSA case: every IAS report in a batch is verified against
the same Intel signing key, so the modulus-dependent fold tables are
precomputed once on host and the per-report modexp runs as batched limb
matmuls on device (reference capability: primitives/enclave-verify/src/
lib.rs:221-228 verify_rsa over the rsa crate).

A `ModContext` freezes: modulus limbs, the 2^(7k) mod n fold table, and the
conditional-subtract count.  `modmul_batch` / `modexp_65537_batch` are the
device entry points; both are bit-identical to Python `pow`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

LIMB_BITS = 7
BASE = 1 << LIMB_BITS


def int_to_limbs(x: int, n: int) -> np.ndarray:
    if x < 0 or x >> (LIMB_BITS * n):
        raise ValueError(f"{x} does not fit in {n} limbs")
    out = np.zeros(n, dtype=np.int8)
    for i in range(n):
        out[i] = x & (BASE - 1)
        x >>= LIMB_BITS
    return out


def limbs_to_int(limbs) -> int:
    x = 0
    for i, limb in enumerate(np.asarray(limbs).astype(np.int64).tolist()):
        x += int(limb) << (LIMB_BITS * i)
    return x


@dataclass(frozen=True)
class ModContext:
    """Precomputed device tables for arithmetic mod a fixed modulus."""

    modulus: int
    nlimbs: int
    mod_limbs: np.ndarray = field(repr=False)
    # fold table: 2^(7k) mod n for k in [nlimbs, 2*nlimbs+6)
    fold_table: np.ndarray = field(repr=False)
    # n·2^k for k = 9..0: shifted-multiple subtraction reaches canonical in
    # 10+1 passes for ANY modulus (value after folds < 2^8·n; 2^9 margin).
    mod_shifts: np.ndarray = field(repr=False)

    @classmethod
    def create(cls, modulus: int) -> "ModContext":
        nl = (modulus.bit_length() + LIMB_BITS - 1) // LIMB_BITS
        mod_limbs = int_to_limbs(modulus, nl).astype(np.int32)
        hi = nl + 6
        fold = np.stack(
            [
                int_to_limbs(pow(2, LIMB_BITS * k, modulus), nl)
                for k in range(nl, 2 * nl + hi)
            ]
        ).astype(np.int32)
        # Post-fold residual is provably < 2^8·n (see _to_canonical);
        # starting at n·2^9 gives 2x margin.
        shifts = np.stack(
            [
                int_to_limbs(modulus << k, nl + 2).astype(np.int32)
                for k in range(9, -1, -1)
            ]
        )
        return cls(
            modulus=modulus,
            nlimbs=nl,
            mod_limbs=mod_limbs,
            fold_table=fold,
            mod_shifts=shifts,
        )

    def to_device_limbs(self, values: list[int]) -> np.ndarray:
        return np.stack([int_to_limbs(v, self.nlimbs) for v in values])

    def from_device_limbs(self, arr) -> list[int]:
        a = np.asarray(arr)
        return [limbs_to_int(row) for row in a.reshape(-1, a.shape[-1])]


# ---------------------------------------------------------------- kernels


def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
    low = x & (BASE - 1)
    carry = x >> LIMB_BITS
    return low + jnp.pad(carry[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])


def _normalize(x: jnp.ndarray, passes: int = 6) -> jnp.ndarray:
    for _ in range(passes):
        x = _carry_pass(x)
    return x


def _cond_sub(x: jnp.ndarray, mod_limbs: jnp.ndarray) -> jnp.ndarray:
    """where(x >= r, x - r, x) — borrow propagation as a lax.scan over the
    limb axis (an unrolled chain makes compile time explode at RSA sizes)."""
    length = x.shape[-1]
    r = jnp.pad(mod_limbs, (0, length - mod_limbs.shape[0]))
    diff = x - r

    def step(borrow, d):
        d2 = d - borrow
        b = (d2 < 0).astype(jnp.int32)
        return b, d2 + b * BASE

    borrow0 = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    borrow, sub = jax.lax.scan(step, borrow0, jnp.moveaxis(diff, -1, 0))
    sub = jnp.moveaxis(sub, 0, -1)
    return jnp.where((borrow == 0)[..., None], sub, x)


def _fold(x: jnp.ndarray, ctx_tables) -> jnp.ndarray:
    """One fold of limbs ≥ nlimbs through the 2^(7k) mod n table; returns
    (…, nlimbs+2) normalized limbs congruent mod n."""
    fold_table, nlimbs = ctx_tables
    pad_spec = [(0, 0)] * (x.ndim - 1)
    low, high = x[..., :nlimbs], x[..., nlimbs:]
    if high.shape[-1] == 0:
        return _normalize(jnp.pad(x, pad_spec + [(0, 2)]))
    table = fold_table[: high.shape[-1]]
    folded = jax.lax.dot_general(
        high.astype(jnp.int32),
        table,
        (((high.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _normalize(jnp.pad(low + folded, pad_spec + [(0, 2)]))


def _fold_partial(x: jnp.ndarray, fold_table, nlimbs) -> jnp.ndarray:
    """Normalized limbs of any length → (…, nlimbs+2) limbs representing a
    value < 2^9·n congruent mod n — the *partial* form chained through a
    modexp.  Canonicalization (the expensive unrolled borrow chains) runs
    once at the end, not per multiplication."""
    tables = (fold_table, nlimbs)
    x = _fold(x, tables)
    for _ in range(3):
        x = _fold(x[..., : nlimbs + 2], tables)
    return x[..., : nlimbs + 2]


def _canonicalize(x: jnp.ndarray, mod_shifts, nlimbs) -> jnp.ndarray:
    """Partial form (< 2^9·n, normalized) → canonical < n via conditional
    subtraction of n·2^9 … n·2^0 plus one residual pass."""
    for k in range(mod_shifts.shape[0]):
        x = _cond_sub(x, mod_shifts[k])
    x = _cond_sub(x, mod_shifts[-1])
    return x[..., :nlimbs]


def _antidiagonal_sums(t: jnp.ndarray) -> jnp.ndarray:
    """(…, L, L) → (…, 2L-1): out[k] = Σ_{i+j=k} t[i, j].

    Shear trick: pad rows to width 2L, flatten, re-split at width 2L-1 —
    row i's element j lands in column i+j — then sum rows.  O(L²) memory,
    no L²×2L one-hot constant."""
    length = t.shape[-1]
    padded = jnp.pad(t, [(0, 0)] * (t.ndim - 2) + [(0, 0), (0, length)])
    flat = padded.reshape(*t.shape[:-2], length * 2 * length)
    flat = flat[..., : length * (2 * length - 1)]
    skew = flat.reshape(*t.shape[:-2], length, 2 * length - 1)
    return skew.sum(axis=-2)


def _modmul_partial(a: jnp.ndarray, b: jnp.ndarray, fold_table, nl):
    """Partial-form product: inputs ≤ nl+2 limbs (< 2^9·n), output partial.

    Each anti-diagonal sums ≤ nl+2 products of 7-bit limbs —
    (nl+2)·127² ≤ 4.8e6 for RSA-2048 (nl=293), inside int32."""
    t = a[..., :, None].astype(jnp.int32) * b[..., None, :].astype(jnp.int32)
    prod = _antidiagonal_sums(t)
    prod = _normalize(jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(0, 5)]))
    return _fold_partial(prod, fold_table, nl)


def make_modmul(ctx: ModContext):
    """Returns a jitted (a, b) → a·b mod n over (…, nlimbs) int limbs,
    canonical output."""
    fold_table = jnp.asarray(ctx.fold_table)
    mod_shifts = jnp.asarray(ctx.mod_shifts)
    nl = ctx.nlimbs

    def modmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        out = _modmul_partial(a, b, fold_table, nl)
        return _canonicalize(out, mod_shifts, nl)

    return jax.jit(modmul)


def make_modexp_65537(ctx: ModContext):
    """Returns a jitted batched s → s^65537 mod n (the RSA verify exponent:
    65537 = 2^16 + 1 ⇒ 16 squarings + 1 multiply).  The chain runs in
    partial form; one canonicalization at the end."""
    fold_table = jnp.asarray(ctx.fold_table)
    mod_shifts = jnp.asarray(ctx.mod_shifts)
    nl = ctx.nlimbs

    def modexp(s: jnp.ndarray) -> jnp.ndarray:
        pad_spec = [(0, 0)] * (s.ndim - 1) + [(0, 2)]
        acc = jnp.pad(s.astype(jnp.int32), pad_spec)
        base = acc

        def square(acc, _):
            return _modmul_partial(acc, acc, fold_table, nl), None

        acc, _ = jax.lax.scan(square, acc, None, length=16)
        out = _modmul_partial(acc, base, fold_table, nl)
        return _canonicalize(out, mod_shifts, nl)

    return jax.jit(modexp)


# ---------------------------------------------------------------- host API


@lru_cache(maxsize=8)
def _cached_ctx(modulus: int) -> ModContext:
    return ModContext.create(modulus)


def modexp_65537_batch(signatures: list[int], modulus: int) -> list[int]:
    """Batched s^65537 mod n on device; bit-identical to pow(s, 65537, n)."""
    ctx = _cached_ctx(modulus)
    fn = _cached_modexp(modulus)
    limbs = ctx.to_device_limbs(signatures)
    return ctx.from_device_limbs(fn(jnp.asarray(limbs)))


@lru_cache(maxsize=8)
def _cached_modexp(modulus: int):
    return make_modexp_65537(_cached_ctx(modulus))
