"""Aggregate / batched BLS signature verification (BASELINE config 4).

The reference verifies miner/TEE BLS signatures one at a time
(utils/verify-bls-signatures/src/lib.rs:85-100: one 2-pairing check per
signature).  At audit scale — thousands of miners submitting signed
verdicts per round — that is 2N Miller loops.  This module re-expresses
the workload TPU-first:

 * **Small-exponent batch test.**  Draw Fiat–Shamir weights r_i (128-bit,
   nonzero, bound to the full (pk, msg, sig) transcript) and check

       e(Π_i sig_i^{r_i}, −g2) · Π_{K} e(Π_{i: pk_i=K} H(m_i)^{r_i}, K) == 1

   which holds iff every per-signature equation holds, except with
   probability ≤ 2^-128 over the weights (the prover cannot pick
   cancelling deviations because r depends on the submitted signatures —
   same argument as ops/podr2.py batch_transcript).

 * **Device G1 folds.**  Both the signature-side fold Π sig_i^{r_i}
   (one flat Pippenger MSM, ops/g1.py) and the per-key message folds
   Π H(m_i)^{r_i} (grouped MSM) run on TPU; this is where the group
   exponentiations — the O(N) 255-bit work — live.

 * **Pairing collapse by key.**  Pairings (host-side, O(1) each) shrink
   from 2N to 1 + #distinct-keys.  In the protocol the dominant batches
   are signed under few keys (the network-wide TeePodr2Pk,
   c-pallets/tee-worker/src/lib.rs:120-121, and per-TEE controller
   keys), so the pairing count is effectively constant.

`verify_signatures` recovers the per-signature verdict bitmap by
bisection when a batch fails, mirroring the ProofBackend contract
(cess_tpu/proof/backend.py).
"""

from __future__ import annotations

import hashlib

from . import bls12_381 as bls
from . import g1
from .bls12_381 import G1Point, G2Point

AGG_DST = b"CESS_TPU_BLS_AGG_V1"
_RHO_BITS = 128

# (pk bytes, msg bytes, sig bytes) — the argument order of the reference
# crate's entry point, verify_bls_signature(sig, msg, key), normalized to
# pk-first like ops/bls12_381.verify.
SigTriple = tuple[bytes, bytes, bytes]


def agg_transcript(seed: bytes, triples: list[SigTriple]) -> bytes:
    """Fiat–Shamir transcript binding the batch weights to every
    (pk, msg, sig) in the batch."""
    h = hashlib.blake2b(digest_size=32)
    h.update(AGG_DST)
    h.update(seed)
    for pk, msg, sig in triples:
        h.update(pk)
        h.update(hashlib.sha256(msg).digest())
        h.update(sig)
    return h.digest()


def batch_weights(transcript: bytes, count: int) -> list[int]:
    """128-bit nonzero weights, deterministic in the transcript."""
    out = []
    for b in range(count):
        digest = hashlib.blake2b(
            AGG_DST + transcript + b.to_bytes(8, "little"), digest_size=16
        ).digest()
        out.append(int.from_bytes(digest, "little") | 1)
    return out


def _hash_points(msgs: list[bytes]) -> list[G1Point]:
    """H(msg) per message, hashing each distinct message once."""
    memo: dict[bytes, G1Point] = {}
    for m in msgs:
        if m not in memo:
            memo[m] = bls.hash_to_g1(m)
    return [memo[m] for m in msgs]


def _weighted_batch_check(
    triples: list[SigTriple], seed: bytes, mesh, device: bool
) -> bool:
    """THE weighted batch equation, shared by the device and host entry
    points: parse, Fiat–Shamir weights, per-key grouping and the pairs
    assembly are single-sourced on purpose — this check IS a consensus
    rule (block import on one node, catch-up batches on another must
    accept identical batches), so the two backends may only differ in
    HOW the two G1 folds are computed, never in what is folded."""
    if not triples:
        return True
    try:
        sig_pts = [G1Point.from_bytes(sig) for _, _, sig in triples]
        # decompress each DISTINCT key once: batches are signed under a
        # handful of authority keys, and G2 decompression (~46 ms of
        # sqrt + subgroup ladder) per TRIPLE was the dominant cost of a
        # 64-block import batch — a dict comprehension pays it before
        # the dict dedups
        pk_pts: dict[bytes, G2Point] = {}
        for pk, _, _ in triples:
            if pk not in pk_pts:
                pk_pts[pk] = G2Point.from_bytes(pk)
    except ValueError:
        return False
    rhos = batch_weights(agg_transcript(seed, triples), len(triples))

    # message-side grouping by distinct public key
    h_pts = _hash_points([msg for _, msg, _ in triples])
    groups: dict[bytes, tuple[list[G1Point], list[int]]] = {}
    for (pk, _, _), h, r in zip(triples, h_pts, rhos):
        pts, rs = groups.setdefault(pk, ([], []))
        pts.append(h)
        rs.append(r)
    keys = list(groups)

    if device:
        # signature-side fold: one flat MSM over the whole batch
        if mesh is not None:
            from ..parallel.msm import msm_sharded

            lhs = msm_sharded(mesh, sig_pts, rhos, bits=_RHO_BITS)
        else:
            lhs = g1.msm(sig_pts, rhos, bits=_RHO_BITS)
        folds = g1.msm_grouped(
            [groups[k][0] for k in keys],
            [groups[k][1] for k in keys],
            bits=_RHO_BITS,
        )
    else:
        lhs = G1Point.infinity()
        for sig, r in zip(sig_pts, rhos):
            lhs = lhs + sig._mul_raw(r)
        folds = []
        for k in keys:
            acc = G1Point.infinity()
            for h, r in zip(*groups[k]):
                acc = acc + h._mul_raw(r)
            folds.append(acc)

    pairs = [(lhs, -bls.G2_GENERATOR)]
    pairs.extend((fold, pk_pts[k]) for k, fold in zip(keys, folds))
    return bls.pairing_check(pairs)


def batch_verify_signatures(
    triples: list[SigTriple], seed: bytes = b"", mesh=None
) -> bool:
    """One combined pairing check for the whole batch.  False if ANY
    signature is invalid (or any pk/sig fails to parse).  mesh: optional
    jax.sharding.Mesh — shards the signature-side fold over its devices
    (parallel/msm.py), bit-identical to the single-device path."""
    return _weighted_batch_check(triples, seed, mesh, device=True)


def verify_signatures(
    triples: list[SigTriple], seed: bytes = b"", mesh=None
) -> list[bool]:
    """Per-signature verdicts: one combined check on the all-honest path,
    bisection to isolate the invalid signatures otherwise."""
    if not triples:
        return []
    if batch_verify_signatures(triples, seed, mesh):
        return [True] * len(triples)
    if len(triples) == 1:
        return [False]
    mid = len(triples) // 2
    return verify_signatures(triples[:mid], seed, mesh) + verify_signatures(
        triples[mid:], seed, mesh
    )


def verify_batch_host(triples: list[SigTriple], seed: bytes = b"") -> bool:
    """The same Fiat–Shamir small-exponent batch equation as
    `batch_verify_signatures` (one shared implementation,
    `_weighted_batch_check`), with the two G1 folds computed HOST-side
    (pure-Python ladders) instead of on device.

    This is the live block-import path (node/service.py): a node's hot
    loop must not pay a JAX trace/compile, and import batches are tiny
    (one block signature + one VRF proof + a handful of extrinsics), so
    a few 128-bit host scalar muls (~2 ms each) beat any device
    round-trip.  Soundness is the point, not speed: unlike
    `verify_aggregate`, the per-triple weights r_i (bound to the full
    transcript, signatures included) make the check hold iff EVERY
    signature individually verifies — a plain aggregate is malleable
    (sig_a+Δ, sig_b−Δ passes), and consensus derives the VRF output
    from the proof BYTES, so malleability there would let an author
    grind epoch randomness.  Verdict is bit-identical to the device
    path by construction."""
    return _weighted_batch_check(triples, seed, mesh=None, device=False)


# ------------------------------------------------------- plain aggregation


def aggregate_pubkeys(pks: list[bytes]) -> bytes:
    """Σ pk_i — the summed verification key (96-byte compressed G2).

    For an aggregate signature over ONE shared message the aggregate
    equation e(agg, −g2) · Π_K e(H(m), K) == 1 collapses to
    e(agg, −g2) · e(H(m), Σ pk) == 1, which is exactly the
    single-signature equation under the summed key — so a whole 2/3
    finality justification enters the weighted batch check as ONE
    SigTriple (node/sync.py verify_justifications_batch), and N
    justifications under the same signer set share one memoized G2
    decompression inside `_weighted_batch_check`.  Raises ValueError on
    a malformed key, like G2Point.from_bytes."""
    acc = G2Point.infinity()
    for pk in pks:
        acc = acc + G2Point.from_bytes(pk)
    return acc.to_bytes()


def aggregate_signatures(sigs: list[bytes]) -> bytes:
    """Σ sig_i — the standard BLS aggregate (48-byte compressed G1)."""
    acc = G1Point.infinity()
    for s in sigs:
        acc = acc + G1Point.from_bytes(s)
    return acc.to_bytes()


def verify_aggregate(
    pks: list[bytes], msgs: list[bytes], agg_sig: bytes
) -> bool:
    """e(agg, −g2) · Π_K e(Σ_{i: pk_i=K} H(m_i), K) == 1.

    Sound only for distinct messages per key (rogue-key/replay caveats are
    the caller's contract, as in every BLS aggregate API); the batched
    `batch_verify_signatures` path above has no such restriction."""
    if len(pks) != len(msgs):
        raise ValueError("pks/msgs length mismatch")
    try:
        agg = G1Point.from_bytes(agg_sig)
        pk_pts = {pk: G2Point.from_bytes(pk) for pk in pks}
    except ValueError:
        return False
    h_pts = _hash_points(msgs)
    groups: dict[bytes, G1Point] = {}
    for pk, h in zip(pks, h_pts):
        groups[pk] = groups.get(pk, G1Point.infinity()) + h
    pairs = [(agg, -bls.G2_GENERATOR)]
    pairs.extend((fold, pk_pts[k]) for k, fold in groups.items())
    return bls.pairing_check(pairs)
