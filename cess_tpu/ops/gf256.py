"""GF(2^8) arithmetic — numpy reference implementation and shared tables.

The reference erasure-codes every 16 MiB segment into fragments (2 data + 1
parity at the protocol layer, reference: runtime/src/lib.rs:1025,
c-pallets/file-bank/src/lib.rs:468 `needed = segments * SEGMENT_SIZE * 1.5`),
with the actual GF(2^8) Reed-Solomon math living off-chain in miner tooling.
This module is the single source of truth for the field: primitive polynomial
0x11D (x^8+x^4+x^3+x^2+1, the standard erasure-coding field), log/exp tables,
and matrix routines used by the host, the C++ core, and as constants baked
into the JAX kernels (ops/rs.py).
"""

from __future__ import annotations

import numpy as np

PRIM_POLY = 0x11D
FIELD = 256

# ---------------------------------------------------------------- tables


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= PRIM_POLY
    exp[255:510] = exp[0:255]
    return exp, log


EXP, LOG = _build_tables()

# Full 256x256 multiplication table (64 KiB) — used by the gather-based JAX
# kernel and the numpy reference.
_a = np.arange(256, dtype=np.int32)
_mul = np.zeros((256, 256), dtype=np.uint8)
_nz = _a[1:]
_mul[1:, 1:] = EXP[(LOG[_nz][:, None] + LOG[_nz][None, :]) % 255]
MUL_TABLE = _mul

# INV[x] = multiplicative inverse (INV[0] = 0 by convention).
INV = np.zeros(256, dtype=np.uint8)
INV[1:] = EXP[255 - LOG[_nz]]


# ---------------------------------------------------------------- scalar ops


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(256) division by zero")
    if a == 0:
        return 0
    return int(EXP[(LOG[a] - LOG[b]) % 255])


def gf_pow(a: int, n: int) -> int:
    if a == 0:
        return 0 if n else 1
    return int(EXP[(int(LOG[a]) * (n % 255)) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of zero")
    return int(INV[a])


# ---------------------------------------------------------------- matrix ops


def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(256) matrix product (XOR-accumulated table lookups)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    assert a.shape[1] == b.shape[0]
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[1]):
        # out ^= MUL_TABLE[a[:, i][:, None], b[i][None, :]]
        np.bitwise_xor(out, MUL_TABLE[a[:, i][:, None], b[i][None, :]], out)
    return out


def mat_inv(m: np.ndarray) -> np.ndarray:
    """GF(256) matrix inverse by Gauss-Jordan elimination."""
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    aug = np.concatenate([m.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = next((r for r in range(col, n) if aug[r, col] != 0), None)
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(256) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = INV[aug[col, col]]
        aug[col] = MUL_TABLE[inv_p, aug[col]]
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= MUL_TABLE[aug[r, col], aug[col]]
    return aug[:, n:].copy()


def cauchy_matrix(k: int, m: int) -> np.ndarray:
    """m x k Cauchy parity matrix: M[j, i] = 1 / ((k + j) ^ i).

    Any k rows of [I_k; M] are invertible, which is the erasure-recovery
    property the fragment/segment accounting relies on.
    """
    if k + m > FIELD:
        raise ValueError("k + m must be <= 256")
    xs = np.arange(k, k + m, dtype=np.int32)
    ys = np.arange(k, dtype=np.int32)
    return INV[(xs[:, None] ^ ys[None, :])].astype(np.uint8)


def encode_matrix(k: int, m: int) -> np.ndarray:
    """(k+m) x k systematic generator [I_k; Cauchy]."""
    return np.concatenate([np.eye(k, dtype=np.uint8), cauchy_matrix(k, m)], axis=0)


def bit_matrix(m: np.ndarray) -> np.ndarray:
    """Expand a GF(256) matrix (r x c) to its GF(2) bit-matrix (8r x 8c).

    Multiplication by a GF(256) constant is GF(2)-linear on the 8 bits of the
    operand: column t of the 8x8 block for constant g is bits(g * x^t).  This
    turns RS encoding into a 0/1 matrix product mod 2 — which the TPU MXU
    executes as a dense int8 matmul (see ops/rs.py bitplane path).

    Bit order: little-endian (bit 0 = LSB) in both row and column blocks.
    """
    m = np.asarray(m, dtype=np.uint8)
    r, c = m.shape
    out = np.zeros((8 * r, 8 * c), dtype=np.uint8)
    for t in range(8):
        prod = MUL_TABLE[m, 1 << t]  # (r, c) = g * x^t
        for q in range(8):
            out[q::8, t::8] = (prod >> q) & 1
    return out


def rs_encode_ref(data: np.ndarray, k: int, m: int) -> np.ndarray:
    """Reference RS encode: data (k, n) uint8 -> parity (m, n) uint8."""
    data = np.asarray(data, dtype=np.uint8)
    assert data.shape[0] == k
    return mat_mul(cauchy_matrix(k, m), data)


def rs_decode_ref(
    shards: np.ndarray, present: list[int], k: int, m: int
) -> np.ndarray:
    """Recover the k data shards from any k surviving shards.

    `shards` is (k_surviving, n) rows ordered to match `present` (global shard
    indices 0..k+m-1, data shards first).
    """
    shards = np.asarray(shards, dtype=np.uint8)
    assert len(present) >= k
    gen = encode_matrix(k, m)
    sub = gen[present[:k]]
    inv = mat_inv(sub)
    return mat_mul(inv, shards[:k])
