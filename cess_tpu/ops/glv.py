"""GLV-accelerated G1 folds — the verify pipeline's fast MSM path.

The PoDR2 batch verification's dominant cost is the H-side grouped MSM:
per proof, 47 random-oracle points multiplied by 160-bit challenge
coefficients (capability match: the per-signature hash/mul work inside
the reference's verify loop, utils/verify-bls-signatures/src/lib.rs:
85-100).  The ops/g1.py ladder prices that at `bits` double-adds per
lane; this module cuts the per-lane work roughly in half by using the
curve's degree-2 GLV endomorphism:

  φ(x, y) = (βx, y)  with  φ(P) = [λ]P  on the r-order subgroup,
  β a non-trivial cube root of unity in Fp, λ = z²−1 (128 bits,
  λ² + λ + 1 ≡ 0 mod r).

Scalars decompose by EXACT integer divmod — k = k2·λ + k1 with
0 ≤ k1 < λ < 2^128 and k2 = k // λ < 2^128 for any k < r — so
[k]P = [k1]P + [k2]φ(P) needs a 64-step 2-bit-window ladder over the
16-entry table {aP + bφP} instead of a 255-step (or, with the
cofactor folded into the scalar, 224-step) double-and-add.  No signed
digits, no rounding: the identity is exact over the integers.

Because φ(P) = [λ]P only holds on the r-order subgroup, the kernel
first clears the cofactor with a fixed [h_eff] chain (h_eff =
0xd201000000010001 has hamming weight 7: 63 doubles + 6 adds — cheaper
than the 64 scalar bits it replaces, and it makes every downstream
scalar reducible mod r).

Everything runs over the ops/g1.py loose-limb field kernels; the Pallas
tile kernel keeps the whole chain (clear → φ table → ladder) VMEM-
resident, and the plain-XLA core is bit-identical for CPU meshes and
the multi-chip dryrun (tests/test_fused.py::TestGlv asserts group-level equality
with the host fold).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .bls12_381 import BLS_X, H_EFF_G1, P, R, G1Point
from .g1 import (
    L,
    LIMB_BITS,
    NP_LIMBS,
    _FOLD_HIGHS,
    _TABLE_OVERRIDE,
    _pow_table,
    _select,
    _sub_pad,
    fp_to_limbs,
    mulm,
    pt_add,
    pt_double,
)

# λ = z² − 1 (z = −BLS_X): the eigenvalue of φ on the r-order subgroup.
LAMBDA = (BLS_X * BLS_X - 1) % R
assert (LAMBDA * LAMBDA + LAMBDA + 1) % R == 0

K_BITS = 128  # both divmod halves fit 128 bits (λ ≈ 2^127.4, r/λ < 2^128)
K_LIMBS = -(-K_BITS // LIMB_BITS) + 1  # 11 limbs + 1 headroom = 132+ bits
N_WINDOWS = K_BITS // 2  # 64 two-bit windows


@lru_cache(maxsize=1)
def beta() -> int:
    """The cube root of unity β with (βx, y) = [λ](x, y) on the subgroup.

    Derived, not transcribed: of the two non-trivial roots of
    t² + t + 1 over Fp, exactly one pairs with λ (the other pairs with
    λ² ≡ −λ−1); pick it by testing against the generator."""
    b = pow(2, (P - 1) // 3, P)
    assert b != 1 and pow(b, 3, P) == 1
    from .bls12_381 import G1_GENERATOR

    lg = G1_GENERATOR.mul(LAMBDA)
    for cand in (b, b * b % P):
        if G1_GENERATOR.x * cand % P == lg.x and G1_GENERATOR.y == lg.y:
            return cand
    raise AssertionError("no cube root of unity matches lambda")


def decompose(k: int) -> tuple[int, int]:
    """k (mod r) → (k1, k2) with k ≡ k1 + k2·λ, both halves < 2^128."""
    k %= R
    k2, k1 = divmod(k, LAMBDA)
    return k1, k2


def decompose_to_limbs(scalars) -> tuple[np.ndarray, np.ndarray]:
    """Scalars → ((K_LIMBS, N), (K_LIMBS, N)) int32 base-4096 digit arrays
    of the divmod halves, limb-major for the ladder kernel."""
    n = len(scalars)
    k1 = np.zeros((n, K_LIMBS), dtype=np.int32)
    k2 = np.zeros((n, K_LIMBS), dtype=np.int32)
    for j, s in enumerate(scalars):
        a, b = decompose(int(s))
        for i in range(K_LIMBS):
            k1[j, i] = a & 0xFFF
            k2[j, i] = b & 0xFFF
            a >>= LIMB_BITS
            b >>= LIMB_BITS
    return k1.T, k2.T


# ------------------------------------------------------------ chain parts
# All helpers trace through ops/g1.py field ops, so they work both in
# plain XLA and inside a Pallas kernel (with _TABLE_OVERRIDE installed).


def _limb_one(like: jnp.ndarray) -> jnp.ndarray:
    limb0 = jax.lax.broadcasted_iota(jnp.int32, like.shape, 0) == 0
    return jnp.where(limb0, 1, 0)


def _infinity(like: jnp.ndarray):
    zero = jnp.zeros_like(like)
    return zero, _limb_one(like), zero


def fixed_mul_static(P3, k: int):
    """[k]P for a Python-static k: runs of doubles as fori_loops, adds
    unrolled at the set bits (trace size ∝ hamming weight)."""
    if k == 0:
        return _infinity(P3[0])
    bits = bin(k)[2:]
    acc = P3
    pos = 1
    while pos < len(bits):
        run = 0
        while pos < len(bits) and bits[pos] == "0":
            run += 1
            pos += 1
        ndbl = run + (1 if pos < len(bits) else 0)
        if ndbl > 2:
            acc = jax.lax.fori_loop(
                0, ndbl, lambda _, a: pt_double(a), acc
            )
        else:
            for _ in range(ndbl):
                acc = pt_double(acc)
        if pos < len(bits):  # the run ended at a set bit
            acc = pt_add(acc, P3)
            pos += 1
    return acc


def _phi(P3, beta_c):
    return mulm(P3[0], beta_c), P3[1], P3[2]


def _glv_table(P3, beta_c):
    """(TX, TY, TZ) each (16, 33, N): T[4b + a] = [a]Q + [b]φ(Q)."""
    inf = _infinity(P3[0])
    q2 = pt_double(P3)
    q3 = pt_add(q2, P3)
    base = [inf, P3, q2, q3]
    phis = [inf, _phi(P3, beta_c), _phi(q2, beta_c), _phi(q3, beta_c)]
    rows = []
    for b in range(4):
        for a in range(4):
            if a == 0:
                rows.append(phis[b])
            elif b == 0:
                rows.append(base[a])
            else:
                rows.append(pt_add(base[a], phis[b]))
    tx = jnp.stack([r[0] for r in rows])
    ty = jnp.stack([r[1] for r in rows])
    tz = jnp.stack([r[2] for r in rows])
    return tx, ty, tz


def _sel16(tx, ty, tz, idx):
    """Per-lane 4-bit table pick via a binary select tree (no gathers —
    Mosaic has no per-lane dynamic indexing along the lane axis)."""
    outs = []
    for t in (tx, ty, tz):
        cur = t
        for bit in (8, 4, 2, 1):
            half = cur.shape[0] // 2
            cond = (idx & bit) != 0
            cur = jnp.where(cond[None, None, :], cur[half:], cur[:half])
        outs.append(cur[0])
    return tuple(outs)


def _window_digits(l1, l2, sh):
    d1 = (l1 >> sh) & 3
    d2 = (l2 >> sh) & 3
    return d1 + 4 * d2


def _glv_ladder(tx, ty, tz, read_window):
    """64-step MSB-first 2-bit ladder: acc = 4·acc + T[window]."""
    def body(i, acc):
        acc = pt_double(pt_double(acc))
        t = _sel16(tx, ty, tz, read_window(i))
        return pt_add(acc, t)

    init = _infinity(tx[0])
    return jax.lax.fori_loop(0, N_WINDOWS, body, init)


def _glv_core(X, Y, Z, k1, k2, beta_c, clear: bool):
    """Shared chain: optional cofactor clear → φ table → ladder.  k1/k2
    are (K_LIMBS, N) int32 digit VALUES (the XLA path); the Pallas kernel
    re-implements only the window read against its refs."""
    pts = (X, Y, Z)
    if clear:
        pts = fixed_mul_static(pts, H_EFF_G1)
    tx, ty, tz = _glv_table(pts, beta_c)

    def read_window(i):
        b = 2 * (N_WINDOWS - 1) - 2 * i  # MSB-first bit position
        limb = b // LIMB_BITS
        sh = b % LIMB_BITS
        l1 = jax.lax.dynamic_index_in_dim(k1, limb, 0, keepdims=False)
        l2 = jax.lax.dynamic_index_in_dim(k2, limb, 0, keepdims=False)
        return _window_digits(l1, l2, sh)

    return _glv_ladder(tx, ty, tz, read_window)


@partial(jax.jit, static_argnames=("clear",))
def _glv_fold_xla(X, Y, Z, k1, k2, clear: bool = True):
    beta_c = jnp.asarray(fp_to_limbs(beta())).reshape(L, 1)
    return _glv_core(X, Y, Z, k1, k2, beta_c, clear)


# ------------------------------------------------------------ pallas path


def _glv_tile_kernel(k1_ref, k2_ref, X_ref, Y_ref, Z_ref, t35_ref, t3_ref,
                     t2_ref, pad_ref, beta_ref, oX_ref, oY_ref, oZ_ref,
                     *, clear: bool):
    """One VMEM-resident tile: clear → table → 64-step ladder with no HBM
    round-trips.  Table/pad constants arrive as inputs (Pallas forbids
    captured array constants) and install via g1._TABLE_OVERRIDE."""
    from jax.experimental import pallas as pl

    token = _TABLE_OVERRIDE.set(
        {
            "pow": {
                h: ref[:]
                for h, ref in zip(_FOLD_HIGHS, (t35_ref, t3_ref, t2_ref))
            },
            "subpad": pad_ref[:],
        }
    )
    try:
        pts = (X_ref[:], Y_ref[:], Z_ref[:])
        if clear:
            pts = fixed_mul_static(pts, H_EFF_G1)
        tx, ty, tz = _glv_table(pts, beta_ref[:])

        def read_window(i):
            b = 2 * (N_WINDOWS - 1) - 2 * i
            limb = b // LIMB_BITS
            sh = b % LIMB_BITS
            l1 = k1_ref[pl.ds(limb, 1), :][0]
            l2 = k2_ref[pl.ds(limb, 1), :][0]
            return _window_digits(l1, l2, sh)

        aX, aY, aZ = _glv_ladder(tx, ty, tz, read_window)
    finally:
        _TABLE_OVERRIDE.reset(token)
    oX_ref[:] = aX
    oY_ref[:] = aY
    oZ_ref[:] = aZ


_GLV_TILE = 512


def _glv_fold_pallas(X, Y, Z, k1, k2, clear: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = X.shape[1]
    tile = min(_GLV_TILE, n)
    spec_pt = pl.BlockSpec((L, tile), lambda i: (0, i))
    spec_sc = pl.BlockSpec((K_LIMBS, tile), lambda i: (0, i))
    t35, t3, t2 = (
        jnp.asarray(_pow_table(NP_LIMBS, h)) for h in _FOLD_HIGHS
    )
    padv = jnp.asarray(np.asarray(_sub_pad())).reshape(L, 1)
    beta_c = jnp.asarray(fp_to_limbs(beta())).reshape(L, 1)
    full = lambda a: pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)  # noqa: E731

    shape = jax.ShapeDtypeStruct((L, n), jnp.int32)
    return pl.pallas_call(
        partial(_glv_tile_kernel, clear=clear),
        grid=(n // tile,),
        in_specs=[
            spec_sc, spec_sc, spec_pt, spec_pt, spec_pt,
            full(t35), full(t3), full(t2), full(padv), full(beta_c),
        ],
        out_specs=[spec_pt, spec_pt, spec_pt],
        out_shape=[shape, shape, shape],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(k1, k2, X, Y, Z, t35, t3, t2, padv, beta_c)


# Module-level jit with `clear` static: the fold compiles once per
# (shape, clear) and is reused — wrapping jax.jit(partial(...)) at each
# call site builds a fresh jit object per verify and retraces every
# time (~65 s/call on the round-4 bench).
_glv_fold_pallas_jit = jax.jit(
    _glv_fold_pallas, static_argnames=("clear",)
)


def glv_fold(X, Y, Z, k1, k2, clear: bool = True):
    """Per-lane [k1 + k2·λ]([h_eff]P) (clear=True) or [k1 + k2·λ]P on
    subgroup inputs (clear=False).  (33, N) limb arrays in, projective
    accumulator triple out.  Fused Pallas tiles on TPU when the lane
    count divides into tiles; bit-identical per-op XLA elsewhere."""
    if jax.default_backend() == "tpu" and X.shape[1] % _GLV_TILE == 0:
        return _glv_fold_pallas_jit(X, Y, Z, k1, k2, clear=clear)
    return _glv_fold_xla(X, Y, Z, k1, k2, clear=clear)


# ------------------------------------------------------------ subgroup


@lru_cache(maxsize=1)
def _r_bits_msb() -> np.ndarray:
    bits = bin(R)[2:]
    return np.asarray([int(b) for b in bits], dtype=np.int32).reshape(-1, 1)


def fixed_mul_bits(P3, bits_arr, nbits: int):
    """[k]P with k given as an MSB-first (nbits, 1) bit array — the
    generic double-and-(select)-add body, fori-looped (small trace)."""
    X, Y, Z = P3

    def body(i, acc):
        acc = pt_double(acc)
        sX, sY, sZ = pt_add(acc, (X, Y, Z))
        b = jax.lax.dynamic_index_in_dim(bits_arr, i, 0, keepdims=False)[0]
        cond = b == 1
        return (
            _select(cond, sX, acc[0]),
            _select(cond, sY, acc[1]),
            _select(cond, sZ, acc[2]),
        )

    return jax.lax.fori_loop(0, nbits, body, _infinity(X))


@jax.jit
def subgroup_mask(X, Y, Z):
    """(N,) int32: 1 where [r]P = ∞ (P in the r-order subgroup, or P = ∞).
    Adversarial σ points must pass this before GLV math may assume the
    λ eigenvalue — the device analog of G1Point.from_bytes' host check
    (ops/bls12_381.py in_subgroup)."""
    from .h2c import _is_zero_mod_p

    bits = jnp.asarray(_r_bits_msb())
    _, _, accZ = fixed_mul_bits((X, Y, Z), bits, bits.shape[0])
    return _is_zero_mod_p(accZ).astype(jnp.int32)
