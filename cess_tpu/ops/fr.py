"""Batched BLS12-381 scalar-field (Fr, r = 255-bit) linear algebra on TPU.

The PoDR2 pipeline's data-heavy arithmetic is all of one shape — "contract a
big array of field elements against a vector of coefficients, mod r":

 * prove:          μ_j  = Σ_c v_c · m_{c,j}    (ops/podr2.py prove())
 * batch combine:  e_j  = Σ_b ρ_b · μ_{b,j}   (ops/podr2.py batch_verify())

Both are integer matrix products.  The TPU has no native big-int type, so
elements are decomposed into base-128 limbs stored as int8 — 7-bit limbs
keep every partial product and a 47-term accumulation inside int32, and int8
operands let XLA route the contraction through the MXU
(`preferred_element_type=int32`).  The pipeline per call:

  1. T[..., i, j] = Σ_k w[k, i] · v[..., k, j]     (int8×int8→int32 matmul)
  2. fold the (i, j) outer-product limbs onto the anti-diagonals i+j
     (a 0/1 tensor contraction — also a matmul)
  3. carry-normalize to base-128
  4. fold high limbs with a 2^(7k) mod r table until 37 limbs remain
  5. conditional subtractions → canonical representative < r

Bit-identical to Python `(sum(w*v) % R)` — asserted in tests — which is what
lets the xla ProofBackend agree with the CPU reference byte for byte.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

LIMB_BITS = 7
BASE = 1 << LIMB_BITS
NLIMBS = (255 + LIMB_BITS - 1) // LIMB_BITS  # 37 limbs for an Fr element


# ---------------------------------------------------------------- host codec


def int_to_limbs(x: int, n: int) -> np.ndarray:
    if x < 0 or x >> (LIMB_BITS * n):
        raise ValueError(f"{x} does not fit in {n} base-128 limbs")
    out = np.zeros(n, dtype=np.int8)
    for i in range(n):
        out[i] = x & (BASE - 1)
        x >>= LIMB_BITS
    return out


def ints_to_limbs(xs, n: int) -> np.ndarray:
    """Iterable of ints -> (len, n) int8 little-endian limb array."""
    return np.stack([int_to_limbs(int(x), n) for x in xs])


def ints_to_words(xs, nbytes: int) -> np.ndarray:
    """Iterable of ints (each < 2^(8·nbytes), nbytes % 4 == 0) →
    (len, nbytes/4) uint32 little-endian words: one bytes pass, no
    per-limb Python loops.  The word form is the shared wire shape the
    vectorised limb codecs below unpack from."""
    buf = b"".join(int(x).to_bytes(nbytes, "little") for x in xs)
    n = len(buf) // nbytes if nbytes else 0
    return np.frombuffer(buf, dtype="<u4").reshape(n, nbytes // 4)


def words_to_limbs(
    words: np.ndarray, limb_bits: int, nlimbs: int, dtype=np.int8
) -> np.ndarray:
    """(…, W) uint32 little-endian words → (…, nlimbs) exact
    base-2^limb_bits limbs — the host mirror of the device unpackers
    (proof/fused.py _mu_words_to_limbs / _u_words_to_limbs), vectorised
    over any batch shape.  Bit-identical to ints_to_limbs /
    g1.scalars_to_limbs for in-range values (tests/test_proof_hotpath.py);
    limb_bits must be ≤ 25 so a limb spans at most two words."""
    if limb_bits > 25:
        raise ValueError("words_to_limbs: limb_bits must be <= 25")
    w = np.asarray(words).astype(np.uint32, copy=False)
    nwords = w.shape[-1]
    out = np.zeros(w.shape[:-1] + (nlimbs,), dtype=np.uint32)
    mask = np.uint32((1 << limb_bits) - 1)
    for i in range(nlimbs):
        lo_bit = limb_bits * i
        wi, sh = lo_bit // 32, lo_bit % 32
        if wi >= nwords:
            break
        val = w[..., wi] >> np.uint32(sh)
        if sh + limb_bits > 32 and wi + 1 < nwords:
            # uint32 wrap above bit 31 is harmless: every kept bit of
            # the straddling word lands below bit limb_bits ≤ 25, and
            # the mask drops the rest — measured 2.6× faster than the
            # uint64 form at (1024, 265, 8)
            val = val | (w[..., wi + 1] << np.uint32(32 - sh))
        out[..., i] = val & mask
    return out.astype(dtype)


def limbs_to_int(limbs) -> int:
    x = 0
    for i, limb in enumerate(np.asarray(limbs).astype(np.int64).tolist()):
        x += int(limb) << (LIMB_BITS * i)
    return x


def limbs_to_ints(arr) -> list[int]:
    """(..., n) limb array -> flat list of ints over the leading axes."""
    a = np.asarray(arr)
    flat = a.reshape(-1, a.shape[-1])
    return [limbs_to_int(row) for row in flat]


@lru_cache(maxsize=None)
def _fold_matrix(li: int, lj: int) -> np.ndarray:
    """(li, lj, li+lj-1) one-hot: out[i, j, i+j] = 1 — maps the outer-product
    limb grid onto anti-diagonals (polynomial multiplication)."""
    out = np.zeros((li, lj, li + lj - 1), dtype=np.int8)
    for i in range(li):
        for j in range(lj):
            out[i, j, i + j] = 1
    return out


@lru_cache(maxsize=None)
def _pow_table(start: int, count: int) -> np.ndarray:
    """(count, NLIMBS) limbs of 2^(7k) mod r for k = start..start+count-1."""
    return ints_to_limbs(
        [pow(2, LIMB_BITS * k, R) for k in range(start, start + count)], NLIMBS
    )


_R_LIMBS = None


def _r_limbs() -> np.ndarray:
    global _R_LIMBS
    if _R_LIMBS is None:
        _R_LIMBS = int_to_limbs(R, NLIMBS).astype(np.int32)
    return _R_LIMBS


# ---------------------------------------------------------------- device ops


def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
    """One base-128 carry propagation pass (length preserved; the caller
    pads so the top carry is always zero)."""
    low = x & (BASE - 1)
    carry = x >> LIMB_BITS
    return low + jnp.pad(carry[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])


def _normalize(x: jnp.ndarray, passes: int = 6) -> jnp.ndarray:
    """Carry-normalize int32 limbs (each < 2^31) to canonical base-128.
    Values ≤ 2^31 need ≤ ceil(24/7)+2 = 6 passes to quiesce."""
    for _ in range(passes):
        x = _carry_pass(x)
    return x


def _carry_exact(x: jnp.ndarray) -> jnp.ndarray:
    """Exact carry propagation as a scan over the limb axis: output limbs
    are strictly < 128 whatever the input chain looks like (the fixed-pass
    _normalize only bounds limbs at <= 128, and a 128 can ripple through
    any fixed number of passes over a run of 127s).  The caller guarantees
    the value fits the limb count, so the final carry is zero."""

    def step(carry, limb):
        t = limb + carry
        return t >> LIMB_BITS, t & (BASE - 1)

    carry0 = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    _, out = jax.lax.scan(step, carry0, jnp.moveaxis(x, -1, 0))
    return jnp.moveaxis(out, 0, -1)


def _cond_sub_r(x: jnp.ndarray) -> jnp.ndarray:
    """x (…, L) normalized limbs → where(x >= r, x - r, x).  Borrow
    propagation runs as a lax.scan over the limb axis (unrolled chains make
    compile time explode)."""
    length = x.shape[-1]
    r = np.zeros(length, dtype=np.int32)
    r[:NLIMBS] = _r_limbs()
    diff = x - jnp.asarray(r)

    def step(borrow, d):
        d2 = d - borrow
        b = (d2 < 0).astype(jnp.int32)
        return b, d2 + b * BASE

    borrow0 = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    borrow, sub = jax.lax.scan(step, borrow0, jnp.moveaxis(diff, -1, 0))
    sub = jnp.moveaxis(sub, 0, -1)
    ge = borrow == 0  # no final borrow ⇒ x >= r
    return jnp.where(ge[..., None], sub, x)


def _fold_once(x: jnp.ndarray) -> jnp.ndarray:
    """One fold of limbs ≥ NLIMBS through the 2^(7k) mod r table; returns a
    normalized (…, NLIMBS+2) array congruent to x mod r."""
    pad_spec = [(0, 0)] * (x.ndim - 1)
    low, high = x[..., :NLIMBS], x[..., NLIMBS:]
    if high.shape[-1] == 0:
        return _normalize(jnp.pad(x, pad_spec + [(0, 2)]))
    table = jnp.asarray(_pow_table(NLIMBS, high.shape[-1]).astype(np.int32))
    folded = jax.lax.dot_general(
        high.astype(jnp.int32),
        table,
        (((high.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _normalize(jnp.pad(low + folded, pad_spec + [(0, 2)]))


def _fold_to_canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Normalized limbs of any length → canonical NLIMBS representative.

    Convergence (static, no data-dependent shapes): the first fold brings
    any ≤64-limb value under 2^259 + 27·128·r < 3500·r (39 limbs); each
    subsequent fold of the 2 surplus limbs shrinks the bound — < 272·r,
    < 34·r, < 20·r — so after four folds 20 conditional subtractions
    finish the job.
    """
    x = _fold_once(x)          # → NLIMBS+2 limbs
    for _ in range(3):
        x = _fold_once(x[..., : NLIMBS + 2])
    x = x[..., : NLIMBS + 2]
    for _ in range(20):
        x = _cond_sub_r(x)
    # canonical < r < 2^255 ⇒ limbs ≥ NLIMBS are provably zero — but the
    # fixed-pass normalize can leave an individual limb at exactly 128, so
    # finish with an exact carry: every canonical output limb is < 128 and
    # safe to recast to int8.
    return _carry_exact(x[..., :NLIMBS])


# int32 accumulator headroom: each anti-diagonal sums ≤ min(Lw,Lv) products
# of two 7-bit limbs over K terms; with Lw ≤ 36 that caps K at
# 2^31 / (127·127·36) ≈ 3698.  Chunk above a conservative bound.
SAFE_CONTRACTION = 2048


def weighted_sum_kernel(
    w: jnp.ndarray, v: jnp.ndarray
) -> jnp.ndarray:
    """Σ_k w[k] · v[..., k, :] mod r.

    w: (K, Lw) int8 limbs; v: (..., K, Lv) int8 limbs.
    Returns (..., NLIMBS) int32 canonical limbs.

    Arbitrary K: contractions beyond SAFE_CONTRACTION are split into
    statically-shaped chunks whose canonical partials are summed and
    re-reduced — overflow-safe for any batch size.
    """
    k = w.shape[0]
    if k > SAFE_CONTRACTION:
        partials = []
        for start in range(0, k, SAFE_CONTRACTION):
            stop = min(start + SAFE_CONTRACTION, k)
            partials.append(
                _weighted_sum_unchunked(
                    w[start:stop],
                    jax.lax.slice_in_dim(v, start, stop, axis=v.ndim - 2),
                )
            )
        # ≤ ceil(K/2048) canonical values: limbs ≤ 127·m, value < m·r —
        # well inside _fold_to_canonical's convergence bound.
        total = partials[0]
        for p in partials[1:]:
            total = total + p
        total = _normalize(
            jnp.pad(total, [(0, 0)] * (total.ndim - 1) + [(0, 3)])
        )
        return _fold_to_canonical(total)
    return _weighted_sum_unchunked(w, v)


def _weighted_sum_unchunked(
    w: jnp.ndarray, v: jnp.ndarray
) -> jnp.ndarray:
    k_axis_w, k_axis_v = 0, v.ndim - 2
    # 1+2: contraction over K and anti-diagonal fold — both matmuls.
    t = jax.lax.dot_general(
        v.astype(jnp.int8),
        w.astype(jnp.int8),
        (((k_axis_v,), (k_axis_w,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (..., Lv, Lw)
    fold = jnp.asarray(
        _fold_matrix(t.shape[-2], t.shape[-1]).astype(np.int32)
    ).reshape(t.shape[-2] * t.shape[-1], -1)
    prod = jax.lax.dot_general(
        t.reshape(*t.shape[:-2], -1),
        fold,
        (((t.ndim - 2,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (..., Lv+Lw-1)
    # 3: carries (pad for growth), 4+5: fold mod r and canonicalize.
    prod = _normalize(jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(0, 5)]))
    return _fold_to_canonical(prod)


weighted_sum_jit = jax.jit(weighted_sum_kernel)


# ---------------------------------------------------------------- public API


def _limb_width(max_value: int) -> int:
    return (max_value.bit_length() + LIMB_BITS - 1) // LIMB_BITS


def mu_aggregate(
    coefficients: list[int], sector_limbs: np.ndarray
) -> np.ndarray:
    """Batched PoDR2 μ: coefficients (the challenge's 20-byte randoms, one
    per challenged chunk) against sector limb arrays.

    sector_limbs: (..., C, S, Lm) int8 — challenged-chunk sector limbs.
    Returns (..., S, NLIMBS) canonical int32 limbs of μ.
    """
    lw = max(1, _limb_width((1 << 160) - 1))
    w = ints_to_limbs(coefficients, lw)
    # Move C next to last for the kernel: (..., S, C, Lm)
    v = np.moveaxis(np.asarray(sector_limbs), -3, -2)
    return np.asarray(weighted_sum_jit(jnp.asarray(w), jnp.asarray(v)))


def combine_mu(rhos: list[int], mu_limbs: np.ndarray) -> np.ndarray:
    """Batch-verification combine: Σ_b ρ_b·μ_b per sector column.

    mu_limbs: (B, S, Lm) int8 limbs.  Returns (S, NLIMBS) int32 limbs.
    """
    lw = max(1, _limb_width(max(rhos)))
    w = ints_to_limbs(rhos, lw)
    v = np.moveaxis(np.asarray(mu_limbs), 0, -2)  # (S, B, Lm)
    return np.asarray(weighted_sum_jit(jnp.asarray(w), jnp.asarray(v)))


def sectors_to_limbs(matrix: list[list[int]]) -> np.ndarray:
    """PoDR2 sector matrix (n × s ints < 2^248) → (n, s, 36) int8 limbs."""
    n = len(matrix)
    s = len(matrix[0])
    lm = _limb_width((1 << 248) - 1)
    out = np.zeros((n, s, lm), dtype=np.int8)
    for i, row in enumerate(matrix):
        for j, m in enumerate(row):
            out[i, j] = int_to_limbs(m, lm)
    return out


def fr_to_limbs(values: list[int]) -> np.ndarray:
    """Canonical Fr values → (len, NLIMBS) int8 limbs."""
    return ints_to_limbs(values, NLIMBS)
