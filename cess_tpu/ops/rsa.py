"""RSA PKCS#1 v1.5 (SHA-256) signature verification — host + batched TPU.

Re-expresses the capability of the reference's IAS-report signature check
(reference: primitives/enclave-verify/src/lib.rs:165-169 — webpki
RSA_PKCS1_2048_8192_SHA256 — and lib.rs:221-228 `verify_rsa` over the rsa
crate; the underlying modexp lives in the vendored ring fork, reference:
utils/ring).  Here the batched verify path runs s^65537 mod n as limb
matmuls on TPU (ops/bigmod.py) with host-side padding checks.

Also provides keygen/sign: the node simulator fabricates attestation
fixtures with them (the reference's tests do the same round-trip,
enclave-verify/src/lib.rs:242-255).
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

from . import bigmod

# DigestInfo prefix for SHA-256 (RFC 8017 §9.2 notes).
SHA256_DIGEST_INFO = bytes.fromhex(
    "3031300d060960864801650304020105000420"
)

F4 = 65537


@dataclass(frozen=True)
class RsaPublicKey:
    n: int
    e: int = F4

    @property
    def size_bytes(self) -> int:
        return (self.n.bit_length() + 7) // 8


@dataclass(frozen=True)
class RsaPrivateKey:
    n: int
    e: int
    d: int

    def public(self) -> RsaPublicKey:
        return RsaPublicKey(self.n, self.e)


# ---------------------------------------------------------------- padding


def emsa_pkcs1_v15(digest: bytes, em_len: int) -> bytes:
    """0x00 0x01 FF… 0x00 DigestInfo ‖ H (RFC 8017 §9.2)."""
    t = SHA256_DIGEST_INFO + digest
    if em_len < len(t) + 11:
        raise ValueError("modulus too small for PKCS#1 v1.5 SHA-256")
    ps = b"\xff" * (em_len - len(t) - 3)
    return b"\x00\x01" + ps + b"\x00" + t


def _check_padding(em: bytes, message: bytes) -> bool:
    digest = hashlib.sha256(message).digest()
    try:
        expected = emsa_pkcs1_v15(digest, len(em))
    except ValueError:
        return False
    return em == expected


# ---------------------------------------------------------------- verify


def verify(key: RsaPublicKey, message: bytes, signature: bytes) -> bool:
    """Host-path PKCS#1 v1.5 SHA-256 verification."""
    if len(signature) != key.size_bytes:
        return False
    s = int.from_bytes(signature, "big")
    if s >= key.n:
        return False
    em = pow(s, key.e, key.n).to_bytes(key.size_bytes, "big")
    return _check_padding(em, message)


def verify_batch(
    key: RsaPublicKey, pairs: list[tuple[bytes, bytes]]
) -> list[bool]:
    """Batched (message, signature) verification: one device modexp batch
    per call (all items share the modulus — the IAS shape: one Intel
    signing key per attestation batch), padding checks on host.
    Bit-identical verdicts to `verify`."""
    if key.e != F4:
        return [verify(key, m, s) for m, s in pairs]
    sigs: list[int] = []
    ok_shape: list[bool] = []
    for _, sig in pairs:
        good = len(sig) == key.size_bytes
        s = int.from_bytes(sig, "big") if good else 0
        good = good and s < key.n
        ok_shape.append(good)
        sigs.append(s if good else 0)
    if not sigs:
        return []
    ems = bigmod.modexp_65537_batch(sigs, key.n)
    out = []
    for good, em_int, (message, _) in zip(ok_shape, ems, pairs):
        if not good:
            out.append(False)
            continue
        em = em_int.to_bytes(key.size_bytes, "big")
        out.append(_check_padding(em, message))
    return out


# ---------------------------------------------------------------- sign


def sign(key: RsaPrivateKey, message: bytes) -> bytes:
    digest = hashlib.sha256(message).digest()
    em = emsa_pkcs1_v15(digest, (key.n.bit_length() + 7) // 8)
    m = int.from_bytes(em, "big")
    return pow(m, key.d, key.n).to_bytes((key.n.bit_length() + 7) // 8, "big")


# ---------------------------------------------------------------- keygen


def _is_probable_prime(n: int, rounds: int = 24) -> bool:
    if n < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, s = n - 1, 0
    while d % 2 == 0:
        d //= 2
        s += 1
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(s - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _random_prime(bits: int, rng=None) -> int:
    get = rng.getrandbits if rng is not None else secrets.randbits
    while True:
        p = get(bits) | (1 << (bits - 1)) | 1
        if p % F4 != 1 and _is_probable_prime(p):
            return p


def keygen(bits: int = 2048, rng=None) -> RsaPrivateKey:
    """Deterministic when given a seeded random.Random (test fixtures)."""
    while True:
        p = _random_prime(bits // 2, rng)
        q = _random_prime(bits // 2, rng)
        if p == q:
            continue
        n = p * q
        if n.bit_length() != bits:
            continue
        phi = (p - 1) * (q - 1)
        d = pow(F4, -1, phi)
        return RsaPrivateKey(n=n, e=F4, d=d)
