"""Batched BLS12-381 G1 arithmetic on TPU: Fp limbs, complete point ops, MSM.

The PoDR2 batch-verification equation (ops/podr2.py) needs three
multi-scalar multiplications per batch — Π σ_b^{ρ_b} over the proofs,
Π H_{b,c}^{ρ_b v_c} over the challenged chunk points, and Π u_j^{e_j} over
the sector generators (capability match: the reference's pairing-side
verify in utils/verify-bls-signatures/src/lib.rs:85-100 and the audit seam
at c-pallets/audit/src/lib.rs:484).  Those MSMs dominate the north-star
workload; this module runs them on device.

Design — no native big-int on TPU, and XLA compile time grows with traced
op count, so every choice below minimises both per-op work and graph size:

 * Fp elements are base-4096 limb vectors (381 bits → 32 limbs), held
   "loose": 33 int32 limbs, each in [0, 4096], value < 2^384 + 8192·p.
   Limb products of loose values fit int32 with headroom
   (4096² · 33 < 2^29), so multiplication is a 33-term shifted
   multiply-accumulate of static pads — no dynamic-update chains, which
   XLA's CPU/TPU backends compile pathologically slowly.  Reduction folds
   limbs ≥ 32 through a 2^(12k) mod p table (one small tensordot); two
   folds restore the loose bound.  No carries are ever resolved exactly
   on device — canonicalisation happens host-side at export, where
   Python big-ints make it a one-liner.
 * Subtraction is borrow-free: a fixed multiple of p is pre-decomposed
   into limbs that are each ≥ 4096, so a + pad − b is non-negative in
   every limb and the carry passes never see negatives.
 * Arrays are limb-major — shape (33, N…) — so the batch axis fills TPU
   vector lanes and every field op is a full-width VPU op.
 * Point ops use the complete projective addition/doubling formulas for
   a = 0 short-Weierstrass curves (Renes–Costello–Batina, EUROCRYPT
   2016, Algorithms 7/9).  E(Fp) for BLS12-381 has odd order, so the
   formulas are exception-free for EVERY input pair — including P = Q,
   P = −Q, and the point at infinity (0 : 1 : 0).  The kernels therefore
   contain no equality tests, no canonicalisation, and no special-case
   selects: they are data-oblivious straight-line code, which is both
   the fast shape for the VPU and the safe shape for adversarial proof
   points engineered to hit doubling/cancellation edges.
 * MSM = per-point MSB-first double-and-add (a lax.fori_loop over the
   scalar bits, batch-vectorised) followed by a pairwise reduction tree
   of complete adds.  The batch axis, not the bit loop, carries the
   parallelism.  `bits` caps the ladder for known-narrow scalars (the
   batch-verification ρ weights are 128-bit).  Batches are padded to a
   power of two with (∞, 0) pairs so distinct jit compilations stay
   logarithmic in the maximum batch size.

Group-level bit-identity against the host reference ops/bls12_381.py
(same affine coordinates out, for every input class) is asserted in
tests/test_g1.py.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .bls12_381 import G1Point, P, R

LIMB_BITS = 12
BASE = 1 << LIMB_BITS
NP_LIMBS = (381 + LIMB_BITS - 1) // LIMB_BITS  # 32 limbs hold an Fp value
L = NP_LIMBS + 1  # loose representation length

R_LIMBS = (255 + LIMB_BITS - 1) // LIMB_BITS  # 22 limbs hold a scalar < r
SCALAR_BITS = 255

B3 = 12  # 3·b for y² = x³ + 4


# ---------------------------------------------------------------- host codec


def fp_to_limbs(x: int, n: int = L) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & (BASE - 1)
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit the requested limb count")
    return out


def limbs_to_fp(limbs) -> int:
    x = 0
    for i, v in enumerate(np.asarray(limbs).astype(object).tolist()):
        x += int(v) << (LIMB_BITS * i)
    return x


def scalars_to_limbs(scalars) -> np.ndarray:
    """Scalars (< r) → (N, 22) int32 little-endian limbs, vectorised:
    one bytes pass plus the shared word-level codec (ops/fr.py
    words_to_limbs) instead of a per-limb Python loop (22 iterations
    per scalar was a measurable slice of the verify host residue at
    B=1024)."""
    from .fr import ints_to_words, words_to_limbs

    if any(not 0 <= int(s) < R for s in scalars):
        raise ValueError("scalar out of range")
    return words_to_limbs(
        ints_to_words(scalars, 32), LIMB_BITS, R_LIMBS, np.int32
    )


def be48_to_limb_rows(be: np.ndarray) -> np.ndarray:
    """(…, 48) big-endian canonical Fp bytes → (…, 33) int32 limbs,
    vectorised (each base-4096 limb pair packs one 3-byte triple; no
    per-element Python big-ints).  Row-major counterpart of
    ops/h2c.py u_bytes_to_limbs, which delegates here."""
    b = np.ascontiguousarray(be).astype(np.int32)
    trip = b.reshape(b.shape[:-1] + (16, 3))
    hi = (trip[..., 0] << 4) | (trip[..., 1] >> 4)
    lo = ((trip[..., 1] & 0xF) << 8) | trip[..., 2]
    pairs = np.stack([lo, hi], axis=-1)  # (…, 16, 2), BE triple order
    pairs = pairs[..., ::-1, :]  # reverse triples → little-endian
    limbs = pairs.reshape(b.shape[:-1] + (NP_LIMBS,))
    out = np.zeros(b.shape[:-1] + (L,), dtype=np.int32)
    out[..., :NP_LIMBS] = limbs
    return out


def points_to_projective(points) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host G1Points → (X, Y, Z) limb arrays ((N, 33) int32 each).
    Infinity encodes as (0 : 1 : 0).  One vectorised byte pass — the
    per-coordinate fp_to_limbs loop cost ~66 Python iterations per
    point, a per-proof tax on every MSM staging."""
    n = len(points)
    if n == 0:
        z = np.zeros((0, L), dtype=np.int32)
        return z, z.copy(), z.copy()
    raw = bytearray(n * 96)
    finite = np.zeros(n, dtype=bool)
    for i, pt in enumerate(points):
        if pt.is_infinity():
            continue
        raw[i * 96 : i * 96 + 48] = pt.x.to_bytes(48, "big")
        raw[i * 96 + 48 : i * 96 + 96] = pt.y.to_bytes(48, "big")
        finite[i] = True
    limbs = be48_to_limb_rows(
        np.frombuffer(bytes(raw), dtype=np.uint8).reshape(n, 2, 48)
    )
    X = limbs[:, 0].copy()
    Y = limbs[:, 1].copy()
    Z = np.zeros_like(X)
    Y[~finite, 0] = 1  # ∞ = (0 : 1 : 0)
    Z[finite, 0] = 1
    return X, Y, Z


def projective_to_points(X, Y, Z) -> list[G1Point]:
    """Loose device limbs → host G1Points.  Canonicalisation (mod p) and
    the Z inversions run host-side; a Montgomery batch inversion turns N
    modular inverses into 3N multiplications plus one modexp."""
    X, Y, Z = (np.asarray(a) for a in (X, Y, Z))
    n = X.shape[0]
    xs = [limbs_to_fp(X[i]) % P for i in range(n)]
    ys = [limbs_to_fp(Y[i]) % P for i in range(n)]
    zs = [limbs_to_fp(Z[i]) % P for i in range(n)]
    # batch-invert the nonzero zs
    idx = [i for i in range(n) if zs[i] != 0]
    prefix = []
    acc = 1
    for i in idx:
        prefix.append(acc)
        acc = acc * zs[i] % P
    inv = pow(acc, P - 2, P)
    zinv = {}
    for j in range(len(idx) - 1, -1, -1):
        i = idx[j]
        zinv[i] = inv * prefix[j] % P
        inv = inv * zs[i] % P
    out = []
    for i in range(n):
        if zs[i] == 0:
            out.append(G1Point.infinity())
        else:
            out.append(G1Point(xs[i] * zinv[i] % P, ys[i] * zinv[i] % P))
    return out


# ---------------------------------------------------------------- tables


@lru_cache(maxsize=None)
def _pow_table(start: int, count: int) -> np.ndarray:
    """(count, 32) limbs of 2^(12k) mod p, k = start…start+count-1."""
    out = np.zeros((count, NP_LIMBS), dtype=np.int32)
    for k in range(count):
        out[k] = fp_to_limbs(pow(2, LIMB_BITS * (start + k), P), NP_LIMBS)
    return out


@lru_cache(maxsize=None)
def _sub_pad() -> np.ndarray:
    """Limbs of a multiple of p, each limb in [4096, 8192), covering the
    loose bound: a + pad − b is non-negative in EVERY limb for loose a, b,
    so subtraction never borrows."""
    floor = sum(BASE << (LIMB_BITS * i) for i in range(L))  # all-4096 limbs
    k = -(-floor // P) + 1
    rem = k * P - floor
    digits = fp_to_limbs(rem)  # each < 4096 by construction
    if k * P >= 1 << (LIMB_BITS * (L + 1)):
        raise AssertionError("sub pad exceeds one extra limb")
    return digits + BASE


# ---------------------------------------------------------------- Fp device
# Field elements are (33, …) int32 arrays, limb-major.  All ops accept any
# trailing batch shape.


def _norm(x: jnp.ndarray, passes: int) -> jnp.ndarray:
    """Value-preserving carry passes for NON-NEGATIVE limbs; callers pick
    `passes` so the result limbs are ≤ 4096 (see per-op bounds)."""
    for _ in range(passes):
        low = x & (BASE - 1)
        carry = x >> LIMB_BITS
        x = low + jnp.pad(
            carry[:-1], [(1, 0)] + [(0, 0)] * (x.ndim - 1)
        )
    return x


# Inside a Pallas kernel the fold/pad tables must come from kernel inputs
# (Pallas rejects captured array constants); the kernel installs them in
# this context variable for the duration of its trace.  A ContextVar (not
# a bare global) keeps concurrent traces from seeing each other's Refs.
import contextvars

_TABLE_OVERRIDE: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "g1_table_override", default=None
)


def _fold(x: jnp.ndarray, rounds: int) -> jnp.ndarray:
    """Normalized limbs (any length, each ≤ 4096) → loose (33, …) limbs,
    congruent mod p.  Each round tensordots the limbs ≥ 32 against the
    2^(12k) mod p table; callers pick `rounds` so the final value is
    < 2^384 + 8192·p (one round per ~2^398 of input bound, two after a
    full product).  The top limbs sliced off at the end are provably
    zero for that bound."""
    tail = [(0, 0)] * (x.ndim - 1)
    for _ in range(rounds):
        k = x.shape[0]
        low, high = x[:NP_LIMBS], x[NP_LIMBS:]
        override = _TABLE_OVERRIDE.get()
        if override is not None:
            # Pallas path: Mosaic has no int32 matmul — expand the small
            # contraction as a broadcast multiply-add over the ≤35 rows.
            if k - NP_LIMBS not in override["pow"]:
                raise KeyError(
                    f"no Pallas fold table for {k - NP_LIMBS} high limbs —"
                    " _FOLD_HIGHS must list every padding the field ops use"
                )
            table = override["pow"][k - NP_LIMBS]  # (K, 32)
            folded = jnp.zeros((NP_LIMBS,) + x.shape[1:], jnp.int32)
            for kk in range(table.shape[0]):
                folded = folded + table[kk].reshape(
                    (NP_LIMBS,) + (1,) * (x.ndim - 1)
                ) * high[kk : kk + 1]
        else:
            table = jnp.asarray(_pow_table(NP_LIMBS, k - NP_LIMBS))
            folded = jnp.tensordot(table.T, high, axes=1)  # (32, …)
        x = jnp.pad(low, [(0, 2)] + tail) + jnp.pad(folded, [(0, 2)] + tail)
        # dot sums ≤ 35·4096·4095 < 2^31; three passes restore ≤ 4096.
        x = _norm(x, 3)
    if x.shape[0] < L:
        x = jnp.pad(x, [(0, L - x.shape[0])] + tail)
    return x[:L]


def _polymul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(33, …) × (33, …) limb convolution → (65, …) int32 via static pads
    (each anti-diagonal sums ≤ 33 products ≤ 4096² ⇒ < 2^29)."""
    tail = [(0, 0)] * (a.ndim - 1)
    acc = jnp.pad(a[0:1] * b, [(0, L - 1)] + tail)
    for i in range(1, L):
        acc = acc + jnp.pad(a[i : i + 1] * b, [(i, L - 1 - i)] + tail)
    return acc


def mulm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    prod = jnp.pad(_polymul(a, b), [(0, 2)] + [(0, 0)] * (a.ndim - 1))
    return _fold(_norm(prod, 3), rounds=2)


def addm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    s = jnp.pad(a + b, [(0, 1)] + [(0, 0)] * (a.ndim - 1))
    return _fold(_norm(s, 2), rounds=1)


def subm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    override = _TABLE_OVERRIDE.get()
    if override is not None:
        pad = override["subpad"].reshape((L,) + (1,) * (a.ndim - 1))
    else:
        pad = jnp.asarray(_sub_pad()).reshape((L,) + (1,) * (a.ndim - 1))
    s = jnp.pad(a + pad - b, [(0, 1)] + [(0, 0)] * (a.ndim - 1))
    return _fold(_norm(s, 2), rounds=1)


def smallmul(a: jnp.ndarray, c: int) -> jnp.ndarray:
    """a · c for a small positive constant (c ≤ 2^17 keeps int32 exact and
    three carry passes restore limbs ≤ 4096)."""
    s = jnp.pad(a * c, [(0, 2)] + [(0, 0)] * (a.ndim - 1))
    return _fold(_norm(s, 3), rounds=1)


# ---------------------------------------------------------------- points
# A point batch is an (X, Y, Z) tuple of (33, …) limb arrays, projective
# coordinates, infinity = (0 : 1 : 0).  Complete formulas: no cases.


def pt_add(p, q):
    """Complete projective addition (Renes–Costello–Batina Alg. 7, a=0).
    Exception-free on BLS12-381's odd-order E(Fp): handles P=Q, P=−Q and
    infinity operands with no branches or selects."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0 = mulm(X1, X2)
    t1 = mulm(Y1, Y2)
    t2 = mulm(Z1, Z2)
    t3 = mulm(addm(X1, Y1), addm(X2, Y2))
    t3 = subm(t3, addm(t0, t1))  # X1Y2 + X2Y1
    t4 = mulm(addm(Y1, Z1), addm(Y2, Z2))
    t4 = subm(t4, addm(t1, t2))  # Y1Z2 + Y2Z1
    ty = mulm(addm(X1, Z1), addm(X2, Z2))
    ty = subm(ty, addm(t0, t2))  # X1Z2 + X2Z1
    t0 = addm(addm(t0, t0), t0)  # 3·X1X2
    t2 = smallmul(t2, B3)  # 3b·Z1Z2
    Z3 = addm(t1, t2)  # Y1Y2 + 3bZ1Z2
    t1 = subm(t1, t2)  # Y1Y2 − 3bZ1Z2
    ty = smallmul(ty, B3)  # 3b(X1Z2 + X2Z1)
    X3 = subm(mulm(t3, t1), mulm(t4, ty))
    Y3 = addm(mulm(t1, Z3), mulm(ty, t0))
    Z3 = addm(mulm(Z3, t4), mulm(t0, t3))
    return X3, Y3, Z3


def pt_double(p):
    """Complete projective doubling (RCB Alg. 9, a=0); same completeness
    guarantees as pt_add, 3 fewer multiplications."""
    X, Y, Z = p
    t0 = mulm(Y, Y)
    Z3 = addm(t0, t0)
    Z3 = addm(Z3, Z3)
    Z3 = addm(Z3, Z3)  # 8Y²
    t1 = mulm(Y, Z)
    t2 = smallmul(mulm(Z, Z), B3)  # 3bZ²
    X3 = mulm(t2, Z3)  # 24bY²Z²
    Y3 = addm(t0, t2)
    Z3 = mulm(t1, Z3)  # 8Y³Z
    t2 = addm(addm(t2, t2), t2)  # 9bZ²
    t0 = subm(t0, t2)  # Y² − 9bZ²
    Y3 = addm(X3, mulm(t0, Y3))
    X3 = mulm(t0, mulm(X, Y))
    X3 = addm(X3, X3)
    return X3, Y3, Z3


def _select(cond, a, b):
    """cond: (…) bool over the batch shape; a, b: (33, …) limb arrays."""
    return jnp.where(cond[None], a, b)


# ------------------------------------------------------------ exact digits


def _prefix_or_and(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Inclusive Kogge–Stone scan of carry/borrow propagation along axis
    0: out_i = g_i | (p_i & (g_{i-1} | (p_{i-1} & …))).  int32 {0,1}."""

    def comb(a, b):
        ga, pa = a
        gb, pb = b
        return gb | (pb & ga), pa & pb

    G, _ = jax.lax.associative_scan(comb, (g, p), axis=0)
    return G


def exact_digits(x: jnp.ndarray, passes: int = 3) -> jnp.ndarray:
    """Non-negative limb array → EXACT base-4096 digits of the same
    value (same length; the caller guarantees the value fits).  `passes`
    value-preserving carry sweeps bound limbs to ≤ 4096 (3 suffices for
    limbs < 2^28), then one Kogge–Stone scan resolves the remaining
    unit carries, which can otherwise cascade the full length."""
    x = _norm(x, passes)
    tail = [(0, 0)] * (x.ndim - 1)
    e = x & (BASE - 1)
    c = x >> LIMB_BITS  # ∈ {0, 1}
    a = e + jnp.pad(c[:-1], [(1, 0)] + tail)
    g = (a >= BASE).astype(jnp.int32)
    p = (a == BASE - 1).astype(jnp.int32)
    cin = jnp.pad(_prefix_or_and(g, p)[:-1], [(1, 0)] + tail)
    return (a + cin) & (BASE - 1)


def limb_product_digits(a: jnp.ndarray, b: jnp.ndarray,
                        out_len: int) -> jnp.ndarray:
    """Exact digits of the integer product of two exact-digit limb
    values: a (ka, …) × b (kb, …) → (out_len, …).  Used to form wide MSM
    scalars (e.g. ρ·v·h_eff) on device instead of per-element host
    big-int work."""
    if min(a.shape[0], b.shape[0]) > 16:
        # anti-diagonal sums of min(ka, kb) 4095² products must stay
        # below 2^28 for exact_digits' three carry passes to be exact
        raise ValueError("limb_product_digits: operand too wide (>16 limbs)")
    ka = a.shape[0]
    tail = [(0, 0)] * (a.ndim - 1)
    kb = b.shape[0]
    width = ka + kb  # conv length ka+kb-1, +1 headroom for carries
    acc = jnp.pad(a[0:1] * b, [(0, width - kb)] + tail)
    for i in range(1, ka):
        acc = acc + jnp.pad(a[i : i + 1] * b, [(i, width - kb - i)] + tail)
    if out_len > width:
        acc = jnp.pad(acc, [(0, out_len - width)] + tail)
    digits = exact_digits(acc, passes=3)
    return digits[:out_len]


# ---------------------------------------------------------------- MSM


def _scalar_bit(scalars: jnp.ndarray, bit_index) -> jnp.ndarray:
    """bit `bit_index` (traced) of (22, …) limb-major scalars → (…) int32."""
    limb = jax.lax.dynamic_index_in_dim(
        scalars, bit_index // LIMB_BITS, axis=0, keepdims=False
    )
    return (limb >> (bit_index % LIMB_BITS)) & 1


def batch_scalar_mul(points, scalars: jnp.ndarray, bits: int = SCALAR_BITS):
    """[s_i]P_i for a batch: MSB-first double-and-add over `bits` bits.

    points: (X, Y, Z) of (33, …); scalars: (22, …) limbs.  Returns a
    projective batch.  `bits` caps the ladder for known-narrow scalars."""
    X, Y, Z = points
    zero = jnp.zeros_like(X)
    one = zero.at[0].set(1)

    def body(i, acc):
        acc = pt_double(acc)
        sX, sY, sZ = pt_add(acc, (X, Y, Z))
        bit = _scalar_bit(scalars, bits - 1 - i) == 1
        return (
            _select(bit, sX, acc[0]),
            _select(bit, sY, acc[1]),
            _select(bit, sZ, acc[2]),
        )

    init = (zero, one, zero)  # infinity
    return jax.lax.fori_loop(0, bits, body, init)


def tree_reduce(points, axis_size: int):
    """Σ over the LAST batch axis (length `axis_size`, a power of two) by
    pairwise halving — log₂ levels of complete adds, no special cases."""
    X, Y, Z = points
    n = axis_size
    while n > 1:
        h = n // 2
        X, Y, Z = pt_add(
            (X[..., :h], Y[..., :h], Z[..., :h]),
            (X[..., h:], Y[..., h:], Z[..., h:]),
        )
        n = h
    return X[..., 0], Y[..., 0], Z[..., 0]


# ------------------------------------------------------------- pallas path


def _ladder_tile_kernel(s_ref, X_ref, Y_ref, Z_ref, t35_ref, t3_ref, t2_ref,
                        pad_ref, oX_ref, oY_ref, oZ_ref, *, bits: int):
    """One VMEM-resident tile of the double-and-add ladder: the whole bit
    loop runs on-chip with no HBM round-trips between steps — the XLA
    per-op path materializes ~50 intermediate (33, N) arrays per bit and
    is bandwidth-bound; this kernel is compute-bound on the VPU.  The
    fold/pad tables arrive as inputs (Pallas forbids captured array
    constants) and are installed via _TABLE_OVERRIDE for the trace."""
    from jax.experimental import pallas as pl

    P = (X_ref[:], Y_ref[:], Z_ref[:])
    zero = jnp.zeros_like(P[0])
    # (no scatter in Pallas: build "limb 0 = 1" with an iota mask)
    limb0 = jax.lax.broadcasted_iota(jnp.int32, zero.shape, 0) == 0
    one = jnp.where(limb0, 1, 0)

    token = _TABLE_OVERRIDE.set(
        {
            "pow": {
                h: ref[:]
                for h, ref in zip(_FOLD_HIGHS, (t35_ref, t3_ref, t2_ref))
            },
            "subpad": pad_ref[:],
        }
    )
    try:

        def body(i, acc):
            acc = pt_double(acc)
            sX, sY, sZ = pt_add(acc, P)
            j = bits - 1 - i
            # dynamic VALUE slicing is not lowerable in-loop; a dynamic
            # REF slice (pl.ds) is
            limb = s_ref[pl.ds(j // LIMB_BITS, 1), :][0]
            bit = ((limb >> (j % LIMB_BITS)) & 1) == 1
            return (
                _select(bit, sX, acc[0]),
                _select(bit, sY, acc[1]),
                _select(bit, sZ, acc[2]),
            )

        aX, aY, aZ = jax.lax.fori_loop(0, bits, body, (zero, one, zero))
    finally:
        _TABLE_OVERRIDE.reset(token)
    oX_ref[:] = aX
    oY_ref[:] = aY
    oZ_ref[:] = aZ


_PALLAS_TILE = 512

# Every distinct high-limb count the field ops feed _fold: mulm pads its
# 65-limb product by 2 (→ 35 high limbs), smallmul pads by 2 (→ 3),
# addm/subm pad by 1 (→ 2).  The Pallas kernel carries one table per
# entry; _fold raises if an op introduces a width not listed here.
_FOLD_HIGHS = (35, 3, 2)


def _batch_scalar_mul_pallas(points, scalars, bits: int):
    """Pallas ladder over (33, N) batches, tiled along the lane axis.
    N must be a power of two (callers pad)."""
    from jax.experimental import pallas as pl

    X, Y, Z = points
    n = X.shape[1]
    tile = min(_PALLAS_TILE, n)
    spec_pt = pl.BlockSpec((L, tile), lambda i: (0, i))
    spec_sc = pl.BlockSpec((R_LIMBS, tile), lambda i: (0, i))

    t35, t3, t2 = (
        jnp.asarray(_pow_table(NP_LIMBS, h)) for h in _FOLD_HIGHS
    )
    padv = jnp.asarray(_sub_pad()).reshape(L, 1)
    full = lambda a: pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)  # noqa: E731

    shape = jax.ShapeDtypeStruct((L, n), jnp.int32)
    return pl.pallas_call(
        partial(_ladder_tile_kernel, bits=bits),
        grid=(n // tile,),
        in_specs=[
            spec_sc, spec_pt, spec_pt, spec_pt,
            full(t35), full(t3), full(t2), full(padv),
        ],
        out_specs=[spec_pt, spec_pt, spec_pt],
        out_shape=[shape, shape, shape],
    )(scalars, X, Y, Z, t35, t3, t2, padv)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@partial(jax.jit, static_argnames=("bits", "group"))
def _msm_kernel(X, Y, Z, scalars, bits=SCALAR_BITS, group=None):
    """(33, N) inputs → per-group MSM.  group=None sums the whole batch
    (result batch 1); group=g reshapes N = B·g and sums within groups."""
    if _use_pallas():
        acc = _batch_scalar_mul_pallas((X, Y, Z), scalars, bits=bits)
    else:
        acc = batch_scalar_mul((X, Y, Z), scalars, bits=bits)
    if group is not None:
        n = X.shape[1]
        acc = tuple(a.reshape(L, n // group, group) for a in acc)
        return tree_reduce(acc, group)
    return tree_reduce(tuple(a[:, None, :] for a in acc), X.shape[1])


def _pad_pow2(arrs: list[np.ndarray], n: int, axis: int = 0, y_index: int = 1):
    """Pad point/scalar batches along `axis` to the next power of two with
    (∞ = (0,1,0), scalar 0) entries; `y_index` names which array is the Y
    coordinate (its padded rows get limb 0 = 1).  Returns (list, size)."""
    m = 1 << max(0, (n - 1).bit_length())
    if m == n:
        return arrs, n
    out = []
    for k, a in enumerate(arrs):
        pad = [(0, 0)] * a.ndim
        pad[axis] = (0, m - n)
        a = np.pad(a, pad)
        if k == y_index:
            sl = [slice(None)] * a.ndim
            sl[axis] = slice(n, m)
            a[tuple(sl)][..., 0] = 1
        out.append(a)
    return out, m


def _prepare(points: list[G1Point], scalars: list[int], bits: int):
    """Shared host preamble for the MSM entry points: validate, reduce
    scalars mod r, enforce the bits cap, encode, pad the batch to a power
    of two, and transpose to the limb-major device layout."""
    if len(points) != len(scalars):
        raise ValueError("points/scalars length mismatch")
    scalars = [s % R for s in scalars]
    if bits < SCALAR_BITS and any(s >> bits for s in scalars):
        raise ValueError("scalar exceeds the bits cap")
    X, Y, Z = points_to_projective(points)
    s = scalars_to_limbs(scalars)
    (X, Y, Z, s), m = _pad_pow2([X, Y, Z, s], len(points))
    return (
        jnp.asarray(X.T),
        jnp.asarray(Y.T),
        jnp.asarray(Z.T),
        jnp.asarray(s.T),
        m,
    )


def msm(
    points: list[G1Point], scalars: list[int], bits: int = SCALAR_BITS
) -> G1Point:
    """Π P_i^{s_i} on device — the batch-verification workhorse.

    Group-level bit-identity with folding G1Point.mul/add on host is
    asserted in tests/test_g1.py.  Every scalar must satisfy
    s % r < 2^bits when `bits` caps the ladder."""
    if not points:
        if len(scalars):
            raise ValueError("points/scalars length mismatch")
        return G1Point.infinity()
    X, Y, Z, s, _ = _prepare(points, scalars, bits)
    rX, rY, rZ = _msm_kernel(X, Y, Z, s, bits=bits)
    return projective_to_points(
        np.asarray(rX).T, np.asarray(rY).T, np.asarray(rZ).T
    )[0]


def msm_grouped(
    points: list[list[G1Point]],
    scalars: list[list[int]],
    bits: int = SCALAR_BITS,
) -> list[G1Point]:
    """Per-group MSMs in one device batch: result[b] = Π_i P[b][i]^{s[b][i]}.

    The groups are padded to a common power-of-two width with (∞, 0)
    pairs.  This is the shape of the verify path's H-side fold and the
    prover's σ fold (47 challenged chunks per proof)."""
    if len(points) != len(scalars):
        raise ValueError("points/scalars length mismatch")
    if not points:
        return []
    width = max(len(g) for g in points)
    g = 1 << max(0, (width - 1).bit_length())
    B = len(points)
    flatpts: list[G1Point] = []
    flatsc: list[int] = []
    inf = G1Point.infinity()
    for prow, srow in zip(points, scalars):
        if len(prow) != len(srow):
            raise ValueError("group length mismatch")
        flatpts.extend(prow)
        flatpts.extend([inf] * (g - len(prow)))
        flatsc.extend(srow)
        flatsc.extend([0] * (g - len(srow)))
    flatsc = [s % R for s in flatsc]
    if bits < SCALAR_BITS and any(s >> bits for s in flatsc):
        raise ValueError("scalar exceeds the bits cap")
    X, Y, Z = points_to_projective(flatpts)
    s = scalars_to_limbs(flatsc)
    # pad the GROUP COUNT to a power of two as well (whole ∞ groups)
    X = X.reshape(B, g, L)
    Y = Y.reshape(B, g, L)
    Z = Z.reshape(B, g, L)
    s = s.reshape(B, g, R_LIMBS)
    (X, Y, Z, s), Bp = _pad_pow2([X, Y, Z, s], B)
    rX, rY, rZ = _msm_kernel(
        jnp.asarray(X.reshape(Bp * g, L).T),
        jnp.asarray(Y.reshape(Bp * g, L).T),
        jnp.asarray(Z.reshape(Bp * g, L).T),
        jnp.asarray(s.reshape(Bp * g, R_LIMBS).T),
        bits=bits,
        group=g,
    )
    return projective_to_points(
        np.asarray(rX).T[:B], np.asarray(rY).T[:B], np.asarray(rZ).T[:B]
    )


# ------------------------------------------------------------ flat MSM
# Pippenger-style windowed-bucket MSM for ONE large flat sum
# Σ_i s_i·P_i — the shape of the batch-verification folds at north-star
# scale.  Cost per point is ~n_windows bucket-contributions (complete
# adds) instead of the ladder's `bits` double-and-adds: at 352-bit
# scalars and 12-bit windows that is ~30 adds/point vs ~700.
#
# TPU mapping: buckets cannot be scatter-accumulated (point addition is
# not an arithmetic scatter op), so each window (a) sorts the lanes by
# digit (lax.sort_key_val), (b) sums runs of equal digits with a
# SEGMENTED associative scan whose combine is the complete add, (c)
# scatters the run totals into the bucket array (unique indices), and
# (d) folds Σ_d d·B_d with the standard suffix-sum identity.  The window
# width is the limb width (12 bits), so the scalar's exact base-4096
# digits ARE the bucket indices — no digit extraction.
#
# Scalars may be WIDER than r (raw integers): nothing here reduces mod
# r, which is exactly what the cofactor-folding contract needs
# (ops/h2c.py — scalars arrive multiplied by h_eff on points whose
# group order is h·r).


def _window_bucket_fold(points, digit, n_buckets: int):
    """Σ_i digit_i·P_i for one window: digit (N,) int32 in [0, 4096)."""
    X, Y, Z = points
    n = X.shape[1]
    order = jnp.argsort(digit)
    sd = jnp.take(digit, order)
    Xs = jnp.take(X, order, axis=1)
    Ys = jnp.take(Y, order, axis=1)
    Zs = jnp.take(Z, order, axis=1)

    def comb(a, b):
        aX, aY, aZ, aid = a
        bX, bY, bZ, bid = b
        same = aid[0] == bid[0]
        sX, sY, sZ = pt_add((aX, aY, aZ), (bX, bY, bZ))
        return (
            _select(same, sX, bX),
            _select(same, sY, bY),
            _select(same, sZ, bZ),
            bid,
        )

    ids = jnp.broadcast_to(sd[None], (1, n))
    cX, cY, cZ, _ = jax.lax.associative_scan(
        comb, (Xs, Ys, Zs, ids), axis=1
    )
    # run totals live at run ends; scatter them into buckets (the dump
    # column absorbs non-end lanes and digit 0)
    nxt = jnp.concatenate([sd[1:], jnp.full((1,), -1, sd.dtype)])
    is_end = (sd != nxt) & (sd != 0)
    idx = jnp.where(is_end, sd, n_buckets)
    bX = jnp.zeros((L, n_buckets + 1), jnp.int32).at[:, idx].set(cX)
    bY = (
        jnp.zeros((L, n_buckets + 1), jnp.int32)
        .at[0]
        .set(1)
        .at[:, idx]
        .set(cY)
    )
    bZ = jnp.zeros((L, n_buckets + 1), jnp.int32).at[:, idx].set(cZ)
    bX, bY, bZ = bX[:, :n_buckets], bY[:, :n_buckets], bZ[:, :n_buckets]
    # Σ_d d·B_d = Σ_{k≥1} Σ_{d≥k} B_d: reverse inclusive scan (suffix
    # sums), zero out lane 0, pairwise tree sum.
    sX, sY, sZ = jax.lax.associative_scan(
        lambda a, b: pt_add(a, b), (bX, bY, bZ), axis=1, reverse=True
    )
    lane0 = jnp.arange(n_buckets) == 0
    sX = jnp.where(lane0[None], 0, sX)
    sY = jnp.where(lane0[None], 1, sY)
    sZ = jnp.where(lane0[None], 0, sZ)
    return tree_reduce((sX, sY, sZ), n_buckets)


@partial(jax.jit, static_argnames=("n_windows",))
def _msm_flat_kernel(X, Y, Z, digits, n_windows: int):
    """digits: (≥n_windows, N) EXACT base-4096 scalar digits.  Returns
    the single MSM total as (33,) limb triples (projective)."""
    zero = jnp.zeros((L,), jnp.int32)
    one = zero.at[0].set(1)

    def body(i, acc):
        j = n_windows - 1 - i
        for _ in range(LIMB_BITS):
            acc = pt_double(acc)
        w = _window_bucket_fold(
            (X, Y, Z),
            jax.lax.dynamic_index_in_dim(digits, j, 0, keepdims=False),
            BASE,
        )
        return pt_add(acc, w)

    aX, aY, aZ = jax.lax.fori_loop(
        0, n_windows, body, (zero, one, zero)
    )
    return aX, aY, aZ


_FLAT_CHUNK = 1 << 20  # lanes per device call: bounds scan memory


def msm_flat_device(points, digits, bits: int):
    """Flat MSM over device-resident limb points with exact-digit device
    scalars.  points: (X, Y, Z) each (33, N); digits: (K, N) with
    K ≥ ⌈bits/12⌉.  Chunks the lane axis (window sums are additive
    across chunks) and returns the projective total as numpy (33,)
    triples."""
    X, Y, Z = points
    n = X.shape[1]
    n_windows = -(-bits // LIMB_BITS)
    if digits.shape[0] < n_windows:
        raise ValueError("digit rows < windows for the requested bits")
    total = None
    for start in range(0, n, _FLAT_CHUNK):
        end = min(start + _FLAT_CHUNK, n)
        part = _msm_flat_kernel(
            X[:, start:end],
            Y[:, start:end],
            Z[:, start:end],
            digits[:, start:end],
            n_windows,
        )
        total = part if total is None else _pt_add_single(total, part)
    return tuple(np.asarray(t) for t in total)


@jax.jit
def _pt_add_single(p, q):
    return pt_add(p, q)


def scalars_to_digits(scalars, n_limbs: int) -> np.ndarray:
    """Raw integer scalars (possibly ≥ r — flat-MSM semantics never
    reduce) → (n_limbs, N) exact base-4096 digits."""
    out = np.zeros((len(scalars), n_limbs), dtype=np.int32)
    for j, s in enumerate(scalars):
        s = int(s)
        if s < 0:
            raise ValueError("negative scalar")
        for k in range(n_limbs):
            out[j, k] = s & (BASE - 1)
            s >>= LIMB_BITS
        if s:
            raise ValueError("scalar exceeds digit width")
    return out.T


def msm_wide(points: list[G1Point], scalars: list[int], bits: int) -> G1Point:
    """Host-list flat-MSM entry: Σ [s_i]P_i with raw (unreduced) integer
    scalars up to `bits` wide — the Pippenger path.  Bit-identity with
    the host fold is asserted in tests/test_msm_flat.py."""
    if len(points) != len(scalars):
        raise ValueError("points/scalars length mismatch")
    if not points:
        return G1Point.infinity()
    n_windows = -(-bits // LIMB_BITS)
    X, Y, Z = points_to_projective(points)
    d = scalars_to_digits(scalars, n_windows)
    (X, Y, Z, d), _ = _pad_pow2([X, Y, Z, d.T], len(points))
    rX, rY, rZ = msm_flat_device(
        (jnp.asarray(X.T), jnp.asarray(Y.T), jnp.asarray(Z.T)),
        jnp.asarray(d.T),
        bits,
    )
    return projective_to_points(rX[None], rY[None], rZ[None])[0]


@partial(jax.jit, static_argnames=("bits",))
def _scalar_mul_kernel(X, Y, Z, scalars, bits=SCALAR_BITS):
    if _use_pallas():
        return _batch_scalar_mul_pallas((X, Y, Z), scalars, bits=bits)
    return batch_scalar_mul((X, Y, Z), scalars, bits=bits)


def scalar_mul_batch(
    points: list[G1Point], scalars: list[int], bits: int = SCALAR_BITS
) -> list[G1Point]:
    """[s_i]P_i per element, returned as host points (test/interop seam)."""
    if not points:
        if len(scalars):
            raise ValueError("points/scalars length mismatch")
        return []
    n = len(points)
    X, Y, Z, s, _ = _prepare(points, scalars, bits)
    rX, rY, rZ = _scalar_mul_kernel(X, Y, Z, s, bits=bits)
    return projective_to_points(
        np.asarray(rX).T[:n], np.asarray(rY).T[:n], np.asarray(rZ).T[:n]
    )
