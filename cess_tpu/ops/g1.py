"""Batched BLS12-381 G1 arithmetic on TPU: Fp limbs, Jacobian ops, MSM.

The PoDR2 batch-verification equation (ops/podr2.py) needs three
multi-scalar multiplications per batch — Π σ_b^{ρ_b} over the proofs,
Π H_{b,c}^{ρ_b v_c} over the challenged chunk points, and Π u_j^{e_j} over
the sector generators (capability match: the reference's pairing-side
verify in utils/verify-bls-signatures/src/lib.rs:85-100 and the audit seam
at c-pallets/audit/src/lib.rs:484).  Those MSMs dominate the north-star
workload; this module runs them on device.

Design — no native big-int on TPU, so:

 * Fp elements are base-128 limb vectors (381 bits → 55 limbs), held
   "loose": 56 int32 limbs, each in [0, 128), value < 2^385 + 256·p.
   Multiplication is a 56-term shifted multiply-accumulate (int32 VPU ops,
   every partial sum < 2^24); reduction folds limbs ≥ 55 through a
   2^(7k) mod p table — two folds restore the loose bound, no per-op
   carries or compares.
 * Canonicalization (rare: equality tests and host export) is a 9-step
   conditional-subtraction ladder (256p … p) using a sign test on the
   most-significant nonzero limb — parallel, no borrow scan — plus one
   exact carry scan.
 * Points are Jacobian (X, Y, Z) limb batches; infinity is Z ≡ 0 (mod p).
   Add/double are branchless: both paths are computed and the special
   cases (either operand at infinity, equal or opposite inputs) resolved
   with selects, so the kernel is data-oblivious and bit-identical to the
   host reference ops/bls12_381.py for every input — including adversarial
   proof points engineered to hit doubling/cancellation edges.
 * MSM = per-point MSB-first double-and-add (a lax.fori_loop over 255
   bits, batch-vectorized) followed by a pairwise reduction tree — the
   batch axis, not the bit loop, is where the parallelism lives.

Bit-identity against ops/bls12_381.py is asserted in tests/test_g1.py.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .bls12_381 import G1Point, P, R

LIMB_BITS = 7
BASE = 1 << LIMB_BITS
NP_LIMBS = (381 + LIMB_BITS - 1) // LIMB_BITS  # 55 limbs hold an Fp value
L = NP_LIMBS + 1  # loose representation length (value < 2^385 + 256p)

R_LIMBS = (255 + LIMB_BITS - 1) // LIMB_BITS  # 37 limbs hold a scalar < r
SCALAR_BITS = 255


# ---------------------------------------------------------------- host codec


def fp_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(L, dtype=np.int32)
    for i in range(L):
        out[i] = x & (BASE - 1)
        x >>= LIMB_BITS
    if x:
        raise ValueError("value does not fit loose Fp limbs")
    return out


def limbs_to_fp(limbs) -> int:
    x = 0
    for i, v in enumerate(np.asarray(limbs).astype(object).tolist()):
        x += int(v) << (LIMB_BITS * i)
    return x


def scalars_to_limbs(scalars) -> np.ndarray:
    """Scalars (< r) → (N, 37) int32 little-endian limbs."""
    out = np.zeros((len(scalars), R_LIMBS), dtype=np.int32)
    for n, s in enumerate(scalars):
        s = int(s)
        if not 0 <= s < R:
            raise ValueError("scalar out of range")
        for i in range(R_LIMBS):
            out[n, i] = s & (BASE - 1)
            s >>= LIMB_BITS
    return out


def points_to_jacobian(points) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host G1Points → (X, Y, Z) limb arrays ((N, 56) int32 each).
    Infinity encodes as (0, 1, 0) like the host reference."""
    n = len(points)
    X = np.zeros((n, L), dtype=np.int32)
    Y = np.zeros((n, L), dtype=np.int32)
    Z = np.zeros((n, L), dtype=np.int32)
    for i, pt in enumerate(points):
        if pt.infinity:
            Y[i] = fp_to_limbs(1)
        else:
            X[i] = fp_to_limbs(pt.x)
            Y[i] = fp_to_limbs(pt.y)
            Z[i] = fp_to_limbs(1)
    return X, Y, Z


def jacobian_to_points(X, Y, Z) -> list[G1Point]:
    """Canonical device limbs → host G1Points (host-side inversion)."""
    X, Y, Z = (np.asarray(a) for a in (X, Y, Z))
    out = []
    for i in range(X.shape[0]):
        z = limbs_to_fp(Z[i]) % P
        if z == 0:
            out.append(G1Point.infinity())
            continue
        zinv = pow(z, P - 2, P)
        z2 = zinv * zinv % P
        out.append(
            G1Point(
                limbs_to_fp(X[i]) * z2 % P,
                limbs_to_fp(Y[i]) * z2 % P * zinv % P,
            )
        )
    return out


# ---------------------------------------------------------------- tables


@lru_cache(maxsize=None)
def _pow_table(start: int, count: int) -> np.ndarray:
    """(count, 55) limbs of 2^(7k) mod p, k = start…start+count-1."""
    out = np.zeros((count, NP_LIMBS), dtype=np.int32)
    for k in range(count):
        v = pow(2, LIMB_BITS * (start + k), P)
        for i in range(NP_LIMBS):
            out[k, i] = v & (BASE - 1)
            v >>= LIMB_BITS
    return out


@lru_cache(maxsize=None)
def _kp_ladder() -> np.ndarray:
    """(9, L) limbs of k·p for k = 256, 128, …, 1 (canonicalization)."""
    return np.stack([fp_to_limbs((1 << (8 - i)) * P) for i in range(9)])


@lru_cache(maxsize=None)
def _sub_pad() -> np.ndarray:
    """Limbs of the smallest multiple of p ≥ 2^385 + 256p (subtraction
    offset: a + pad - b stays non-negative for loose a, b)."""
    bound = (1 << 385) + 256 * P
    k = -(-bound // P)
    return fp_to_limbs(k * P)


# ---------------------------------------------------------------- Fp device


def _norm(x: jnp.ndarray, passes: int = 6) -> jnp.ndarray:
    """Fixed carry passes: int32 limbs (|.| < 2^24 growth per pass is fine,
    negative limbs use arithmetic-shift floor semantics) → limbs in
    [0, 128] (a single limb may sit at exactly 128; the fold/canon steps
    tolerate it)."""
    for _ in range(passes):
        low = x & (BASE - 1)
        carry = x >> LIMB_BITS
        x = low + jnp.pad(
            carry[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)]
        )
    return x


def _fold_to_loose(x: jnp.ndarray) -> jnp.ndarray:
    """Normalized limbs of any length ≥ 55 → loose (…, 56) limbs, value
    < 2^385 + 256p, congruent mod p."""
    for _ in range(2):
        low, high = x[..., :NP_LIMBS], x[..., NP_LIMBS:]
        if high.shape[-1] == 0:
            x = jnp.pad(low, [(0, 0)] * (x.ndim - 1) + [(0, 2)])
        else:
            table = jnp.asarray(_pow_table(NP_LIMBS, high.shape[-1]))
            folded = jax.lax.dot_general(
                high,
                table,
                (((high.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            x = jnp.pad(
                low + folded, [(0, 0)] * (x.ndim - 1) + [(0, 2)]
            )
        x = _norm(x)
    return x[..., :L]


def _polymul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(…, 56) × (…, 56) limb convolution → (…, 111) int32 (each
    anti-diagonal sums ≤ 56 products < 2^14 ⇒ < 2^20, no overflow)."""
    out = jnp.zeros((*a.shape[:-1], 2 * L - 1), dtype=jnp.int32)
    for i in range(L):
        out = out.at[..., i : i + L].add(a[..., i : i + 1] * b)
    return out


def mulm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # pad before normalizing: the top anti-diagonal can carry out (its sum
    # is up to 56·127² ≈ 2^20, two limbs of headroom absorb the chain).
    prod = _polymul(a, b)
    prod = jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(0, 2)])
    return _fold_to_loose(_norm(prod))


def addm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    s = jnp.pad(a + b, [(0, 0)] * (a.ndim - 1) + [(0, 1)])
    return _fold_to_loose(_norm(s))


def subm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    pad = jnp.asarray(_sub_pad())
    s = jnp.pad(a + pad - b, [(0, 0)] * (a.ndim - 1) + [(0, 1)])
    return _fold_to_loose(_norm(s))


def _scan_flags(gen: jnp.ndarray, prop: jnp.ndarray) -> jnp.ndarray:
    """Carry-lookahead: given per-limb generate/propagate flags, return the
    carry INTO each limb (log-depth associative scan, no sequential pass)."""

    def combine(a, b):  # b is the later segment
        ga, pa = a
        gb, pb = b
        return gb | (pb & ga), pa & pb

    g_out, _ = jax.lax.associative_scan(
        combine, (gen.astype(jnp.int32), prop.astype(jnp.int32)), axis=-1
    )
    # carry into limb i = carry out of prefix [0..i-1]
    return jnp.pad(
        g_out[..., :-1], [(0, 0)] * (gen.ndim - 1) + [(1, 0)]
    )


def _carry_fix(x: jnp.ndarray) -> jnp.ndarray:
    """Limbs in [0, 128] (post-_norm) → strictly [0, 128), exactly."""
    cin = _scan_flags(x == BASE, x == BASE - 1)
    return (x + cin) & (BASE - 1)


def _borrow_sub(x: jnp.ndarray, y: jnp.ndarray):
    """Exact conditional subtract: both strictly normalized; returns
    (x - y if x >= y else x, ge).  Borrow propagation is a carry-lookahead
    scan on the per-limb differences."""
    d = x - y
    bin_ = _scan_flags(d < 0, d == 0)
    out = d - bin_
    bout_last = (out[..., -1] < 0).astype(jnp.int32)
    out = out + (out < 0) * BASE
    ge = bout_last == 0
    return jnp.where(ge[..., None], out, x), ge


def canon(x: jnp.ndarray) -> jnp.ndarray:
    """Loose → canonical representative < p (exact limbs in [0, 128))."""
    x = _carry_fix(_norm(x))
    ladder = _kp_ladder()
    for k in range(ladder.shape[0]):
        x, _ = _borrow_sub(x, jnp.asarray(ladder[k]))
    return x


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """x ≡ 0 (mod p) for loose x → (…,) bool."""
    return jnp.all(canon(x) == 0, axis=-1)


# ---------------------------------------------------------------- points
# A point batch is a (X, Y, Z) tuple of (…, 56) int32 limb arrays.


def _select(cond, a, b):
    return jnp.where(cond[..., None], a, b)


def pt_double(p):
    """dbl-2009-l (a = 0): branchless; infinity (Z ≡ 0) and y ≡ 0 inputs
    propagate to Z3 ≡ 0 through the 2·Y·Z factor."""
    X1, Y1, Z1 = p
    A = mulm(X1, X1)
    B = mulm(Y1, Y1)
    C = mulm(B, B)
    t = addm(X1, B)
    D = mulm(t, t)
    D = subm(D, addm(A, C))
    D = addm(D, D)  # 2((X+B)^2 - A - C)
    E = addm(addm(A, A), A)
    F = mulm(E, E)
    X3 = subm(F, addm(D, D))
    C8 = addm(addm(C, C), addm(C, C))
    C8 = addm(C8, C8)
    Y3 = subm(mulm(E, subm(D, X3)), C8)
    Z3 = mulm(addm(Y1, Y1), Z1)
    return X3, Y3, Z3


def pt_add(p, q):
    """General Jacobian add (add-2007-bl) with branchless special cases:
    p or q at infinity, p == q (falls through to double), p == -q
    (infinity).  Cost: one add + one double + four canon comparisons."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = mulm(Z1, Z1)
    Z2Z2 = mulm(Z2, Z2)
    U1 = mulm(X1, Z2Z2)
    U2 = mulm(X2, Z1Z1)
    S1 = mulm(mulm(Y1, Z2), Z2Z2)
    S2 = mulm(mulm(Y2, Z1), Z1Z1)
    H = subm(U2, U1)
    rr = subm(S2, S1)

    h_zero = is_zero(H)
    r_zero = is_zero(rr)
    p_inf = is_zero(Z1)
    q_inf = is_zero(Z2)

    I = mulm(addm(H, H), addm(H, H))
    J = mulm(H, I)
    r2 = addm(rr, rr)
    V = mulm(U1, I)
    X3 = subm(mulm(r2, r2), addm(J, addm(V, V)))
    Y3 = subm(mulm(r2, subm(V, X3)), addm(mulm(S1, J), mulm(S1, J)))
    Z3 = mulm(mulm(addm(Z1, Z2), addm(Z1, Z2)), H)
    Z3 = mulm(Z1, Z2)
    Z3 = mulm(addm(Z3, Z3), H)

    dX, dY, dZ = pt_double(p)

    zero = jnp.zeros_like(X3)
    # equal inputs → double; opposite → infinity (Z = exact 0)
    is_dbl = h_zero & r_zero & ~p_inf & ~q_inf
    is_inf_out = h_zero & ~r_zero & ~p_inf & ~q_inf
    X3 = _select(is_dbl, dX, X3)
    Y3 = _select(is_dbl, dY, Y3)
    Z3 = _select(is_dbl, dZ, Z3)
    Z3 = _select(is_inf_out, zero, Z3)
    # either operand at infinity → the other
    X3 = _select(q_inf, X1, _select(p_inf, X2, X3))
    Y3 = _select(q_inf, Y1, _select(p_inf, Y2, Y3))
    Z3 = _select(q_inf, Z1, _select(p_inf, Z2, Z3))
    return X3, Y3, Z3


# ---------------------------------------------------------------- MSM


def _scalar_bit(scalars: jnp.ndarray, bit_index) -> jnp.ndarray:
    """bit `bit_index` (traced) of (…, 37) limb scalars → (…,) int32."""
    limb = jax.lax.dynamic_index_in_dim(
        scalars, bit_index // LIMB_BITS, axis=scalars.ndim - 1, keepdims=False
    )
    return (limb >> (bit_index % LIMB_BITS)) & 1


def batch_scalar_mul(points, scalars: jnp.ndarray):
    """[s_i]P_i for a batch: MSB-first double-and-add over 255 bits.

    points: (X, Y, Z) of (N, 56); scalars: (N, 37) limbs.  Returns a
    Jacobian batch (N, 56)×3."""
    X, Y, Z = points
    zero = jnp.zeros_like(X)
    one = jnp.zeros_like(X).at[..., 0].set(1)

    def body(i, acc):
        aX, aY, aZ = pt_double(acc)
        sX, sY, sZ = pt_add((aX, aY, aZ), (X, Y, Z))
        bit = _scalar_bit(scalars, SCALAR_BITS - 1 - i) == 1
        return (
            _select(bit, sX, aX),
            _select(bit, sY, aY),
            _select(bit, sZ, aZ),
        )

    init = (zero, one, zero)  # infinity
    return jax.lax.fori_loop(0, SCALAR_BITS, body, init)


def tree_reduce(points):
    """Σ of a Jacobian batch by pairwise halving (log₂ N levels of batched
    adds).  Returns a batch of size 1."""
    X, Y, Z = points
    one = jnp.zeros((1, L), dtype=jnp.int32).at[0, 0].set(1)
    while X.shape[0] > 1:
        n = X.shape[0]
        if n % 2:  # pad with infinity
            X = jnp.concatenate([X, jnp.zeros((1, L), jnp.int32)])
            Y = jnp.concatenate([Y, one])
            Z = jnp.concatenate([Z, jnp.zeros((1, L), jnp.int32)])
            n += 1
        h = n // 2
        X, Y, Z = pt_add(
            (X[:h], Y[:h], Z[:h]), (X[h:], Y[h:], Z[h:])
        )
    return X, Y, Z


@jax.jit
def _msm_kernel(X, Y, Z, scalars):
    acc = batch_scalar_mul((X, Y, Z), scalars)
    rX, rY, rZ = tree_reduce(acc)
    return canon(rX), canon(rY), canon(rZ)


def msm(points: list[G1Point], scalars: list[int]) -> G1Point:
    """Π P_i^{s_i} on device — the batch-verification workhorse.

    Bit-identical to folding G1Point.mul/add on host (tests/test_g1.py)."""
    if len(points) != len(scalars):
        raise ValueError("points/scalars length mismatch")
    if not points:
        return G1Point.infinity()
    X, Y, Z = points_to_jacobian(points)
    s = scalars_to_limbs([s % R for s in scalars])
    rX, rY, rZ = _msm_kernel(
        jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z), jnp.asarray(s)
    )
    return jacobian_to_points(rX, rY, rZ)[0]


@jax.jit
def _scalar_mul_canon_kernel(X, Y, Z, scalars):
    rX, rY, rZ = batch_scalar_mul((X, Y, Z), scalars)
    return canon(rX), canon(rY), canon(rZ)


def scalar_mul_batch(points: list[G1Point], scalars: list[int]) -> list[G1Point]:
    """[s_i]P_i per element, returned as host points (test/interop seam)."""
    X, Y, Z = points_to_jacobian(points)
    s = scalars_to_limbs([s % R for s in scalars])
    rX, rY, rZ = _scalar_mul_canon_kernel(
        jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z), jnp.asarray(s)
    )
    return jacobian_to_points(rX, rY, rZ)
