"""The read plane: stateless light clients + horizontally scalable
read replicas.

The validator loop serves consensus; this package serves READERS.  A
`LightClient` (light/client.py) holds only the genesis hash and an
initial validator keyset, and verifies everything else it learns —
finality justifications, era-boundary validator-set handoffs, and
storage reads — against proofs pulled over RPC.  A `ReplicaService`
(light/replica.py) is the keyless follower those clients talk to: it
batch-verifies justifications in one weighted pairing, maintains the
FINALIZED state commitment from per-block deltas, and serves read
proofs — replica count, not validator count, is the scaling knob for
the "millions of users" scenario (ROADMAP item 4).
"""

from .client import LightClient, LightClientError, StaleAnchorError
from .replica import FinalizedView, ReplicaService

__all__ = [
    "FinalizedView",
    "LightClient",
    "LightClientError",
    "ReplicaService",
    "StaleAnchorError",
]
