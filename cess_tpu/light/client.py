"""Stateless finality-verifying light client.

A `LightClient` trusts exactly two things: the genesis hash and an
initial validator BLS keyset (both derivable from the chain spec —
ChainSpec.genesis_hash / validator_keys).  Everything else is verified,
never believed:

 * **Finality.**  It pulls the latest justification over
   `chain_getJustification`, fetches the covered HEADER over
   `light_syncHeaders`, recomputes the block hash from the header
   bytes (sync.header_hash — the body rides as its extRoot
   commitment), and checks the 2/3 BLS aggregate against its tracked
   keyset (sync.verify_justification).  Only then does the header's
   state root become an anchor.

 * **Validator-set handoffs.**  At every new anchor the NEXT tracked
   set is read out of the just-justified state itself: one
   `state_getProofBatch` round trip proves `staking:validators` and
   `session:keys` against the anchored root, and a validator that is
   neither in the proven session-key registry nor already tracked
   refuses the handoff — the set evolves with zero trust extension.

 * **Reads.**  `read`/`read_batch` prove N keys in one round trip and
   check every wire against the client's OWN anchored root
   (checkpoint.verify_read_batch) — the server's claimed root is never
   trusted.  A replica whose finalized view moved past the anchor
   answers the typed -32014; the client re-anchors once and retries.

The client keeps no chain state: no blocks, no trie, no database —
(genesis, anchor, keyset) is the whole client, which is what lets a
replica fleet serve arbitrarily many of them (light/replica.py).
"""

from __future__ import annotations

from ..chain import checkpoint, smt
from ..node.rpc import RpcError, rpc_call
from ..node.sync import Justification, header_hash, verify_justification

# Root-mismatch RPC code (state_getProofBatch): the replica's finalized
# view advanced past the pinned anchor — re-anchor and retry.
ROOT_MISMATCH = -32014


class LightClientError(Exception):
    """A proof, header, or justification failed verification — or the
    server could not serve one.  Nothing is adopted on this path."""


class StaleAnchorError(LightClientError):
    """The server finalized PAST the anchor being adopted mid-handshake
    (typed -32014 on the handoff read) — a liveness race, not an
    attack: re-syncing lands on the newer justification."""


class LightClient:
    """See module docstring.  `keys` maps validator name → BLS public
    key bytes; the tracked set after N handoffs may differ from it."""

    def __init__(
        self,
        genesis: str,
        keys: dict[str, bytes],
        host: str = "127.0.0.1",
        port: int = 9944,
        timeout: float = 10.0,
    ) -> None:
        if not keys:
            raise ValueError("light client needs an initial keyset")
        self.genesis = genesis
        self.keys = dict(keys)
        self.host = host
        self.port = port
        self.timeout = timeout
        # The justified anchor: {"number", "hash", "root"} — the ONE
        # commitment reads verify against.  None until the first sync.
        self.anchor: dict | None = None
        # telemetry counters (the load generator sums these)
        self.justifications_verified = 0
        self.handoffs = 0

    @classmethod
    def from_spec(cls, spec, host: str = "127.0.0.1", port: int = 9944,
                  timeout: float = 10.0) -> "LightClient":
        return cls(spec.genesis_hash(), spec.validator_keys(),
                   host=host, port=port, timeout=timeout)

    # ------------------------------------------------------------ wire

    def _call(self, method: str, *params):
        return rpc_call(self.host, self.port, method, list(params),
                        timeout=self.timeout)

    # ------------------------------------------------------ finality

    def sync(self, _retried: bool = False) -> dict:
        """Advance the anchor to the server's latest justification and
        return it.  Raises LightClientError when the server serves
        nothing newer, a forged/stale justification, or a handoff that
        does not prove out.  Retries ONCE when the server finalizes
        past the anchor mid-handshake (StaleAnchorError — a race on a
        live chain, not a refusal)."""
        try:
            wire = self._call("chain_getJustification", None)
            just = Justification.from_json(wire)
        except (RpcError, OSError) as e:
            raise LightClientError(f"no justification served: {e}")
        except (KeyError, TypeError, ValueError) as e:
            raise LightClientError(f"malformed justification: {e!r}")
        if self.anchor is not None:
            if (just.number == self.anchor["number"]
                    and just.block_hash == self.anchor["hash"]):
                return self.anchor  # already anchored there
            if just.number <= self.anchor["number"]:
                # a server must never serve finality that rewinds the
                # client — same height with a different hash would be
                # conflicting 2/3 quorums (accountable-safety violation)
                raise LightClientError(
                    f"server finality at #{just.number} is behind or "
                    f"conflicts with anchor #{self.anchor['number']}")
        try:
            self._adopt(just)
        except StaleAnchorError:
            if _retried:
                raise
            return self.sync(_retried=True)
        return self.anchor

    def _adopt(self, just: Justification) -> None:
        hdrs = self._call("light_syncHeaders", just.number, 1)
        if not isinstance(hdrs, list) or not hdrs:
            raise LightClientError(
                f"no header served for justified #{just.number}")
        hdr = hdrs[0].get("header") if isinstance(hdrs[0], dict) else None
        try:
            got_hash = header_hash(self.genesis, hdr)
            number = int(hdr["number"])
            root = str(hdr["stateHash"])
        except (KeyError, TypeError, ValueError) as e:
            raise LightClientError(f"malformed header: {e!r}")
        if number != just.number or got_hash != just.block_hash:
            raise LightClientError(
                "served header does not hash to the justified block")
        if not verify_justification(
            just, self.genesis, list(self.keys), self.keys
        ):
            raise LightClientError(
                "justification refused: forged aggregate, sub-quorum, "
                "or signers outside the tracked set")
        self.justifications_verified += 1
        # era handoff BEFORE adopting: a root whose validator set we
        # cannot prove is not an anchor
        self._handoff(root)
        self.anchor = {
            "number": just.number, "hash": just.block_hash, "root": root,
        }

    def _handoff(self, root: str) -> None:
        """Refresh the tracked keyset from the just-justified state:
        `staking:validators` names the set, `session:keys` proves each
        member's registered key.  A member with neither a proven
        session key nor an already-tracked key refuses the WHOLE
        handoff — adopting an unprovable key would extend trust."""
        reads = [("staking", "validators", None), ("session", "keys", None)]
        try:
            (ok_v, validators), (ok_k, skeys) = self._proven_reads(
                root, reads)
        except RpcError as e:
            # -32014 here means the server finalized past this anchor
            # between serving the justification and the handoff read —
            # refuse the adoption; sync() retries onto the newer one
            if e.code == ROOT_MISMATCH:
                raise StaleAnchorError(f"anchor superseded mid-sync: {e}")
            raise LightClientError(f"handoff reads refused: {e}")
        if not ok_v or not isinstance(validators, list) or not validators:
            raise LightClientError(
                "validator set unreadable at the justified root")
        if not ok_k or not isinstance(skeys, dict):
            skeys = {}
        new: dict[str, bytes] = {}
        for name in validators:
            name = str(name)
            key = skeys.get(name)
            if not isinstance(key, bytes):
                key = self.keys.get(name)
            if not isinstance(key, bytes):
                raise LightClientError(
                    f"handoff refused: validator {name!r} has no "
                    "provable session key and is not tracked")
            new[name] = key
        if new != self.keys:
            self.handoffs += 1
            self.keys = new

    # --------------------------------------------------------- reads

    def read(self, pallet: str, attr: str, key=None) -> tuple[bool, object]:
        """One verified read at the anchored root: (present, value)."""
        return self.read_batch([(pallet, attr, key)])[0]

    def read_batch(
        self, reads: list[tuple], _retried: bool = False
    ) -> list[tuple[bool, object]]:
        """N verified reads in ONE RPC round trip, every proof checked
        against the client's own justified anchor root.  Re-anchors
        once on the typed root-mismatch refusal (the replica finalized
        past our anchor), then retries."""
        norm = [
            (r[0], r[1], r[2] if len(r) == 3 else None)
            for r in (tuple(r) for r in reads)
        ]
        if self.anchor is None:
            self.sync()
        try:
            return self._proven_reads(self.anchor["root"], norm)
        except RpcError as e:
            if e.code == ROOT_MISMATCH and not _retried:
                self.sync()
                return self.read_batch(norm, _retried=True)
            raise LightClientError(f"batch refused: {e}")

    def _proven_reads(
        self, root: str, reads: list[tuple[str, str, object]]
    ) -> list[tuple[bool, object]]:
        got = self._call(
            "state_getProofBatch",
            [[p, a, k] for p, a, k in reads], root,
        )
        proofs = got.get("proofs") if isinstance(got, dict) else None
        if not isinstance(proofs, list) or len(proofs) != len(reads):
            raise LightClientError("malformed proof batch reply")
        try:
            return checkpoint.verify_read_batch(
                root, reads, [p["proof"] for p in proofs]
            )
        except smt.ProofError as e:
            raise LightClientError(
                f"proof does not commit to the justified root: {e}")
        except (KeyError, TypeError, ValueError) as e:
            raise LightClientError(f"malformed proof wire: {e!r}")
