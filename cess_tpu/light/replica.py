"""The read-replica tier: keyless followers that scale the read plane.

A `ReplicaService` is a `NodeService` with the authorship half removed
and the verification half industrialised:

 * **Keyless.**  `authority_sk` is forced to None, which disables every
   signing path in the base service — no blocks, no finality votes, no
   OCW heartbeats.  A replica can never equivocate because it can never
   sign; compromising one leaks no key and forges no finality.

 * **Batch finality.**  Incoming justifications land in a queue
   (mirroring the PR-16 block-import pipeline shape) and are verified
   in batches: each justification is ONE aggregate-signature triple
   (Σ pk over its signers, the finality payload, the aggregate), so N
   of them fold into a single weighted pairing check
   (sync.verify_justifications_batch).  Amortised cost per
   justification drops with batch size; a refused batch falls back to
   per-item verification, so accept/reject decisions are bit-identical
   to the serial path.

 * **Finalized read plane.**  A `FinalizedView` — path→encoding dict +
   sparse-Merkle tree, NO runtime — tracks the FINALIZED state
   commitment, advanced by replaying the per-block leaf deltas the
   import path already records.  Every proof the replica serves
   (state_getProof / state_getProofBatch, node/rpc.py routes through
   `read_plane`) therefore verifies against a root a light client can
   justify for itself; the replica never serves unfinalised state it
   would have to walk back.

Replica count is the horizontal scaling knob: replicas follow
validators, light clients fan out across replicas, and the validator
set never sees read traffic (bench.py BENCH_ONLY=light measures the
one-vs-two-replica scaling).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

from ..chain import checkpoint, smt
from ..node import metrics as m
from ..node.service import NodeService
from ..node.sync import Justification, verify_justifications_batch

# Most justifications one drain folds into a single weighted pairing.
# Matches SYNC_RANGE_MAX — a catch-up range arrives as at most one
# batch — and bounds how long the drainer holds verdicts back.
JUST_BATCH_MAX = 64

# Verdict memory for (number, hash) pairs already decided — the replica
# analogue of the import-result cache: gossip redelivers the same
# justification from every peer, and a cached verdict answers without
# re-queueing it.
JUST_RESULT_CACHE_MAX = 2048


class FinalizedView:
    """The replica's finalized state commitment: a path→encoding map
    plus its sparse-Merkle tree, advanced by per-block deltas only.
    There is no runtime behind it — it cannot execute anything, only
    commit and prove.  Guarded by the owning service's _lock."""

    def __init__(self, enc: dict[bytes, bytes], number: int) -> None:
        self._enc = dict(enc)
        self.smt = smt.SparseMerkleTree(self._enc)
        self.number = number

    def root_hex(self) -> str:
        return self.smt.root().hex()

    def apply(self, delta: list, number: int) -> str:
        """Replay one block's leaf delta (chain/state.py DeltaEntry
        list) onto the view; returns the new root."""
        writes: dict[bytes, bytes | None] = {}
        for pallet, attr, kenc, _old, new in delta:
            label = checkpoint.leaf_label(pallet, attr)
            path = smt.key_path(label, kenc if kenc is not None else b"")
            writes[path] = new
            if new is None:
                self._enc.pop(path, None)
            else:
                self._enc[path] = new
        if writes:
            self.smt.update(writes)
        self.number = number
        return self.root_hex()

    def prove(self, pallet: str, attr: str, key=None) -> dict:
        """Read proof against the FINALIZED root — same wire and same
        keyed-map validation as StateDB.prove, so rpc.py serves either
        interchangeably."""
        keyed = (pallet, attr) in checkpoint.KEYED_MAPS
        if keyed != (key is not None):
            raise ValueError(
                f"{pallet}.{attr} is "
                f"{'a keyed map' if keyed else 'one leaf'} — key "
                f"{'required' if keyed else 'must be omitted'}"
            )
        label = checkpoint.leaf_label(pallet, attr)
        kenc = b"" if key is None else checkpoint.canon_bytes(key)
        path = smt.key_path(label, kenc)
        value = self.smt.get(path)
        return {
            "root": self.root_hex(),
            "path": path.hex(),
            "proof": self.smt.prove(path).to_wire(),
            "value": None if value is None else value.hex(),
        }


class ReplicaService(NodeService):
    """See module docstring.  Construct with a spec only — any
    authority argument is meaningless here and not accepted."""

    def __init__(self, spec, registry=None, **kw) -> None:
        super().__init__(spec, authority=None, registry=registry, **kw)
        # The base service derives a dev signing key for the slot
        # author on dev-seeded chains; a replica must hold NO key at
        # all — this also switches off votes, OCW and heartbeats.
        self.authority_sk = None
        # Finalized read plane, seeded from the genesis state (the
        # StateDB is exactly the genesis commitment at construction).
        self.read_plane = FinalizedView(
            self.statedb.leaf_encodings(), 0)  # guarded-by: _lock
        # Justification pipeline (the PR-16 import-queue shape): one
        # drainer folds queued justifications into one pairing.  The
        # condition wraps the service lock, so `with self._just_cv`
        # IS `with self._lock` plus wait/notify.
        self._just_queue: deque[Justification] = deque()  # guarded-by: _just_cv
        self._just_queued: set[tuple[int, str]] = set()  # guarded-by: _just_cv
        self._just_results: OrderedDict[tuple[int, str], bool] = (
            OrderedDict())  # guarded-by: _just_cv
        self._just_draining = False  # guarded-by: _just_cv
        self._just_cv = threading.Condition(self._lock)
        reg = self.registry
        self.m_light_justs = m.Counter(
            "cess_light_justifications_verified",
            "justifications this replica verified for the read plane",
            reg)
        self.m_light_batch = m.Counter(
            "cess_light_batch_pairings",
            "weighted pairing checks spent verifying justification "
            "batches (amortisation = verified / pairings)", reg)
        self.m_replica_reads = m.Counter(
            "cess_replica_reads_total",
            "read proofs served from the finalized read plane", reg)
        self.m_replica_proof = m.Histogram(
            "cess_replica_proof_seconds",
            "read-proof build time (per state_getProof* request)",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0),
            registry=reg)

    # ------------------------------------------------- batch finality

    def handle_justification(
        self, just: Justification, _verified: bool = False
    ) -> bool:
        """Route unverified justifications through the batch pipeline;
        already-verified ones (pending-buffer replays from _post_block,
        or the drainer applying its own verdicts) take the base path
        directly and then advance the read plane."""
        if _verified:
            got = super().handle_justification(just, _verified=True)
            if got:
                with self._lock:
                    self._advance_read_plane()
            return got
        key = (just.number, just.block_hash)
        with self._just_cv:
            if just.number <= self.finalized_number:
                return False
            if key in self._just_results:
                return self._just_results[key]
            if key not in self._just_queued:
                self._just_queue.append(just)
                self._just_queued.add(key)
        return self.flush_justifications(wait_for=key)

    def handle_justifications(self, justs: list[Justification]) -> int:
        """The batch entry point (sync catch-up ranges): enqueue the
        whole range FIRST, then drain — so one weighted pairing covers
        the lot instead of one pairing per height."""
        keys = []
        with self._just_cv:
            for just in sorted(justs, key=lambda j: j.number):
                key = (just.number, just.block_hash)
                if just.number <= self.finalized_number:
                    continue
                if (key not in self._just_queued
                        and key not in self._just_results):
                    self._just_queue.append(just)
                    self._just_queued.add(key)
                keys.append(key)
        advanced = 0
        for key in keys:
            if self.flush_justifications(wait_for=key):
                advanced += 1
        return advanced

    def flush_justifications(
        self, wait_for: tuple[int, str] | None = None
    ) -> bool:
        """Become the drainer (or wait for the active one): pop up to
        JUST_BATCH_MAX queued justifications, verify them in ONE
        weighted pairing OUTSIDE the lock, then apply the verified ones
        in height order.  Returns the advanced?-verdict for `wait_for`
        once it is decided (False for None)."""
        while True:
            with self._just_cv:
                if wait_for is not None and wait_for in self._just_results:
                    return self._just_results[wait_for]
                if not self._just_queue:
                    if not self._just_draining:
                        # queue drained and nobody is verifying — a
                        # wait_for not in results was superseded
                        # (finalized past it before its turn)
                        return False
                    self._just_cv.wait(0.5)
                    continue
                if self._just_draining:
                    if wait_for is None:
                        return False  # the active drainer will get to it
                    self._just_cv.wait(0.5)
                    continue
                self._just_draining = True
                batch = []
                while self._just_queue and len(batch) < JUST_BATCH_MAX:
                    batch.append(self._just_queue.popleft())
                validators = list(self.spec.validators)
                keyset = dict(self.keys)
                genesis = self.genesis
            # the expensive part — pairings — runs without the lock so
            # reads keep flowing while the batch verifies
            verdicts = None
            try:
                stats = {"pairings": 0}
                verdicts = verify_justifications_batch(
                    batch, genesis, validators, keyset, stats=stats)
                self.m_light_batch.inc(stats.get("pairings", 0))
            finally:
                with self._just_cv:
                    self._just_draining = False
                    if verdicts is None:  # verification crashed
                        for just in batch:
                            self._just_queued.discard(
                                (just.number, just.block_hash))
                        self._just_cv.notify_all()
            if verdicts is None:
                return False
            with self._just_cv:
                decided = sorted(
                    zip(batch, verdicts), key=lambda bv: bv[0].number)
                for just, ok in decided:
                    key = (just.number, just.block_hash)
                    adv = False
                    if ok:
                        self.m_light_justs.inc()
                        adv = self.handle_justification(
                            just, _verified=True)
                    self._just_results[key] = adv
                    self._just_queued.discard(key)
                while len(self._just_results) > JUST_RESULT_CACHE_MAX:
                    self._just_results.popitem(last=False)
                self._just_cv.notify_all()

    # --------------------------------------------------- read plane

    def _advance_read_plane(self) -> None:  # holds-lock: _lock
        """Roll the finalized view forward to the finalized head by
        replaying recorded per-block deltas.  When a delta fell out of
        the bounded cache (deep catch-up) the view rebases wholesale
        from the live trie — but only when the finalized head IS the
        live head, because the StateDB commits to head state."""
        while self.read_plane.number < self.finalized_number:
            number = self.read_plane.number + 1
            blk = self.block_by_number.get(number)
            delta = (None if blk is None
                     else self._state_deltas.get(blk.hash(self.genesis)))
            if delta is None:
                if (self.finalized_number == self.rt.state.block_number
                        and self.finalized_hash == self.head_hash):
                    self.read_plane = FinalizedView(
                        self.statedb.leaf_encodings(),
                        self.finalized_number)
                # else: the gap block's delta is gone and head is past
                # the finalized anchor mid-import — the next finality
                # advance lands on a replayable window
                return
            got = self.read_plane.apply(delta, number)
            if blk is not None and blk.state_hash != got:
                # loud, like StateDB.check_oracle: a divergent replay
                # means the served proofs would commit to a wrong root
                raise RuntimeError(
                    f"read-plane divergence at #{number}: replayed "
                    f"root {got} != committed {blk.state_hash}")

    def restore_checkpoint(self, blob, head, justification=None) -> bool:
        """Warp-sync rebases the read plane wholesale: after a restore
        the live trie IS the finalized post-state of the restored
        head."""
        ok = super().restore_checkpoint(blob, head, justification)
        if ok:
            with self._lock:
                self.read_plane = FinalizedView(
                    self.statedb.leaf_encodings(), self.finalized_number)
        return ok
