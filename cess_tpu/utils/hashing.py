"""Host-side hashing helpers and the Hash64 digest convention.

The reference stores file/segment/fragment digests as 64 ASCII hex characters
(`Hash([u8;64])`, reference: primitives/common/src/lib.rs:16) — i.e. the hex
string of a 32-byte hash, not the raw bytes.  We keep that convention at the
protocol layer (`Hash64`) because deal/file identity, dedup, and restoral
orders all key on it.

Hashing stays on the host CPU (SURVEY.md §7: only field/coding math goes to
TPU); the C++ native core (native/chaincore.cpp) carries bit-identical
SHA-256/BLAKE2b for the host runtime path, tested in tests/test_native.py.
"""

from __future__ import annotations

import hashlib


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def blake2b_256(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


class Hash64(str):
    """64-char lowercase hex digest (the reference's on-chain hash type)."""

    __slots__ = ()

    def __new__(cls, value: str) -> "Hash64":
        value = value.lower()
        if len(value) != 64 or any(c not in "0123456789abcdef" for c in value):
            raise ValueError(f"Hash64 must be 64 hex chars, got {value!r}")
        return super().__new__(cls, value)

    @classmethod
    def of(cls, data: bytes) -> "Hash64":
        return cls(hashlib.sha256(data).hexdigest())

    @classmethod
    def zero(cls) -> "Hash64":
        return cls("0" * 64)

    def raw(self) -> bytes:
        return bytes.fromhex(self)

    def ascii_bytes(self) -> bytes:
        """The 64 ASCII bytes as stored on-chain by the reference."""
        return self.encode("ascii")
