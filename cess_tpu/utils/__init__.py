from . import codec, hashing, rng  # noqa: F401
