"""Deterministic protocol RNG.

Every validator must derive the *same* challenge from shared block randomness
(reference: c-pallets/audit/src/lib.rs:1019-1048 `random_number` /
`generate_challenge_random`; sampling loops at lib.rs:846-940 and
c-pallets/file-bank/src/functions.rs:201-297).  The reference seeds a per-use
RNG from (parent-block randomness, seed counter); we reproduce those
*semantics* — deterministic, replayable, domain-separated — with a
blake2b-based counter construction that is identical across the Python host,
the C++ core, and test vectors.

Stream definition (canonical, frozen):
    state_0   = blake2b_256(seed || u64le(domain_counter))
    block_i   = blake2b_256(state_0 || u64le(i))        i = 0, 1, ...
    stream    = block_0 || block_1 || ...
u32/u64 draws consume 4/8 bytes little-endian from the stream.
`randrange(n)` consumes ceil(bitlen(n-1)/8) bytes per rejection-sampling
attempt, so the distribution is exact and replayable for any n.
"""

from __future__ import annotations

import hashlib


def _blake(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


class ProtocolRng:
    """Deterministic, domain-separated random stream."""

    def __init__(self, seed: bytes, domain: int = 0) -> None:
        self._state = _blake(bytes(seed) + domain.to_bytes(8, "little"))
        self._buf = b""
        self._counter = 0

    def _refill(self) -> None:
        self._buf += _blake(self._state + self._counter.to_bytes(8, "little"))
        self._counter += 1

    def take(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._refill()
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def u32(self) -> int:
        return int.from_bytes(self.take(4), "little")

    def u64(self) -> int:
        return int.from_bytes(self.take(8), "little")

    def randrange(self, n: int) -> int:
        """Uniform in [0, n) by rejection sampling.

        Draws ceil(bitlen/8) bytes per attempt so arbitrarily large n works
        (a u64-only rejection loop would never terminate for n > 2**64).
        """
        if n <= 0:
            raise ValueError("randrange needs n > 0")
        if n == 1:
            return 0
        nbytes = ((n - 1).bit_length() + 7) // 8
        space = 1 << (8 * nbytes)
        limit = space - (space % n)
        while True:
            v = int.from_bytes(self.take(nbytes), "little")
            if v < limit:
                return v % n

    def sample_distinct(self, population: int, count: int) -> list[int]:
        """`count` distinct indices in [0, population), in draw order.

        Mirrors the reference's rejection-loop style of repeatedly drawing
        until a fresh index appears (audit/src/lib.rs:906-914 draws 47 distinct
        chunk indices this way).
        """
        if count > population:
            raise ValueError("cannot sample more than population")
        seen: set[int] = set()
        out: list[int] = []
        while len(out) < count:
            v = self.randrange(population)
            if v not in seen:
                seen.add(v)
                out.append(v)
        return out

    def shuffle(self, items: list) -> list:
        """Deterministic Fisher-Yates; returns a new list."""
        items = list(items)
        for i in range(len(items) - 1, 0, -1):
            j = self.randrange(i + 1)
            items[i], items[j] = items[j], items[i]
        return items
