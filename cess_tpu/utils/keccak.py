"""Keccak-256 (original pad 0x01 — NOT FIPS SHA3's 0x06).

The EVM's hash (used by chain/evm.py for CREATE addresses, storage-slot
derivation in contracts, and the KECCAK256 opcode).  hashlib ships only
the FIPS-202 variant, whose domain-separation padding differs, so the
permutation is implemented here.  Capability match: the reference gets
this from Frontier's sp-core hashing (pallet_evm, reference:
runtime/src/lib.rs:1322-1344).

Checked against the standard empty-string / "abc" vectors in
tests/test_evm.py.
"""

from __future__ import annotations

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

_ROTATIONS = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_MASK = (1 << 64) - 1


def _rol(x: int, n: int) -> int:
    n &= 63
    return ((x << n) | (x >> (64 - n))) & _MASK


def _keccak_f(state: list[int]) -> None:
    """keccak-f[1600] over a 5x5 lane state (state[x * 5 + y])."""
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [
            state[x * 5] ^ state[x * 5 + 1] ^ state[x * 5 + 2]
            ^ state[x * 5 + 3] ^ state[x * 5 + 4]
            for x in range(5)
        ]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                state[x * 5 + y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y * 5 + (2 * x + 3 * y) % 5] = _rol(
                    state[x * 5 + y], _ROTATIONS[x][y]
                )
        # chi
        for x in range(5):
            for y in range(5):
                state[x * 5 + y] = b[x * 5 + y] ^ (
                    (~b[((x + 1) % 5) * 5 + y] & _MASK)
                    & b[((x + 2) % 5) * 5 + y]
                )
        # iota
        state[0] ^= rc


def keccak256(data: bytes) -> bytes:
    """32-byte Keccak-256 digest (rate 136, pad10*1 with marker 0x01)."""
    rate = 136
    state = [0] * 25
    # pad
    padded = bytearray(data)
    padded.append(0x01)
    while len(padded) % rate:
        padded.append(0x00)
    padded[-1] ^= 0x80
    # absorb
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[i * 8 : i * 8 + 8], "little")
            x, y = i % 5, i // 5
            state[x * 5 + y] ^= lane
        _keccak_f(state)
    # squeeze (32 bytes < rate: one block)
    out = bytearray()
    for i in range(rate // 8):
        x, y = i % 5, i // 5
        out += state[x * 5 + y].to_bytes(8, "little")
        if len(out) >= 32:
            break
    return bytes(out[:32])
