"""Canonical deterministic codec (SCALE-compatible core).

The reference chain hashes SCALE-encoded challenge info to form the quorum
proposal (reference: c-pallets/audit/src/lib.rs:376-378) — every validator must
produce byte-identical encodings or quorum never commits.  This module provides
the minimal SCALE-compatible primitives the protocol needs: little-endian fixed
ints, compact (parity-scale-codec) length prefixes, vectors, and byte strings.

Pure python, dependency-free; used by both the host protocol layer and the
golden-vector tests that anchor the C++/JAX implementations.
"""

from __future__ import annotations


def encode_uint(value: int, nbytes: int) -> bytes:
    """Fixed-width little-endian unsigned int (SCALE fixed integer)."""
    if value < 0 or value >= (1 << (8 * nbytes)):
        raise ValueError(f"value {value} out of range for u{8 * nbytes}")
    return value.to_bytes(nbytes, "little")


def decode_uint(data: bytes, offset: int, nbytes: int) -> tuple[int, int]:
    if offset + nbytes > len(data):
        raise ValueError("truncated input decoding fixed integer")
    return int.from_bytes(data[offset : offset + nbytes], "little"), offset + nbytes


def encode_compact(value: int) -> bytes:
    """SCALE compact integer encoding.

    mode 0b00: single byte, value << 2          (0..=63)
    mode 0b01: two bytes  (value << 2) | 0b01   (64..=2**14-1)
    mode 0b10: four bytes (value << 2) | 0b10   (2**14..=2**30-1)
    mode 0b11: (len-4) in upper 6 bits, then len little-endian bytes
    """
    if value < 0:
        raise ValueError("compact encoding is unsigned")
    if value < 1 << 6:
        return bytes([value << 2])
    if value < 1 << 14:
        return ((value << 2) | 0b01).to_bytes(2, "little")
    if value < 1 << 30:
        return ((value << 2) | 0b10).to_bytes(4, "little")
    nbytes = (value.bit_length() + 7) // 8
    if nbytes > 67:
        raise ValueError("compact value too large")
    return bytes([((nbytes - 4) << 2) | 0b11]) + value.to_bytes(nbytes, "little")


def decode_compact(data: bytes, offset: int = 0) -> tuple[int, int]:
    """Decode a compact integer, rejecting truncated and non-canonical forms
    (parity-scale-codec errors on non-minimal encodings; so must we, or
    byte-distinct inputs alias to one value and the quorum hash diverges)."""
    if offset >= len(data):
        raise ValueError("truncated input decoding compact")
    first = data[offset]
    mode = first & 0b11
    if mode == 0b00:
        return first >> 2, offset + 1
    if mode == 0b01:
        if offset + 2 > len(data):
            raise ValueError("truncated input decoding compact u16")
        value = int.from_bytes(data[offset : offset + 2], "little") >> 2
        if value < 1 << 6:
            raise ValueError("non-canonical compact encoding")
        return value, offset + 2
    if mode == 0b10:
        if offset + 4 > len(data):
            raise ValueError("truncated input decoding compact u32")
        value = int.from_bytes(data[offset : offset + 4], "little") >> 2
        if value < 1 << 14:
            raise ValueError("non-canonical compact encoding")
        return value, offset + 4
    nbytes = (first >> 2) + 4
    if offset + 1 + nbytes > len(data):
        raise ValueError("truncated input decoding compact big")
    value = int.from_bytes(data[offset + 1 : offset + 1 + nbytes], "little")
    if value < 1 << 30 or value < 1 << (8 * (nbytes - 1)):
        raise ValueError("non-canonical compact encoding")
    return value, offset + 1 + nbytes


def encode_bytes(data: bytes) -> bytes:
    """Compact-length-prefixed byte string (SCALE Vec<u8>)."""
    return encode_compact(len(data)) + data


def decode_bytes(data: bytes, offset: int = 0) -> tuple[bytes, int]:
    n, offset = decode_compact(data, offset)
    if offset + n > len(data):
        raise ValueError("truncated input decoding byte string")
    return data[offset : offset + n], offset + n


def encode_vec(items: list[bytes]) -> bytes:
    """Compact-length-prefixed vector of pre-encoded items."""
    out = [encode_compact(len(items))]
    out.extend(items)
    return b"".join(out)


def encode_bool(value: bool) -> bytes:
    return b"\x01" if value else b"\x00"


class Writer:
    """Accumulating canonical encoder."""

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "Writer":
        self._parts.append(encode_uint(v, 1))
        return self

    def u16(self, v: int) -> "Writer":
        self._parts.append(encode_uint(v, 2))
        return self

    def u32(self, v: int) -> "Writer":
        self._parts.append(encode_uint(v, 4))
        return self

    def u64(self, v: int) -> "Writer":
        self._parts.append(encode_uint(v, 8))
        return self

    def u128(self, v: int) -> "Writer":
        self._parts.append(encode_uint(v, 16))
        return self

    def compact(self, v: int) -> "Writer":
        self._parts.append(encode_compact(v))
        return self

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(bytes(b))
        return self

    def bytes(self, b: bytes) -> "Writer":
        self._parts.append(encode_bytes(b))
        return self

    def boolean(self, v: bool) -> "Writer":
        self._parts.append(encode_bool(v))
        return self

    def finish(self) -> bytes:
        return b"".join(self._parts)
