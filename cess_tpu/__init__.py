"""cess_tpu — a TPU-native storage-proof framework.

A ground-up re-design of the capabilities of the CESS decentralized storage
chain (reference: /root/reference, omahs/cess): file deals, erasure-coded
segment/fragment accounting, miner registry + staking rewards, TEE-worker
registry, and the PoDR2 random-challenge audit protocol — with every
cryptographic / coding hot path (Reed-Solomon over GF(2^8), PoDR2 tag & proof
math over the BLS12-381 scalar field, SHA-256/Merkle, BLS pairing, RSA modexp)
implemented as batched, vmapped JAX kernels that compile to TPU, behind a
pluggable ``ProofBackend`` with a bit-identical CPU reference.

Layout (maps to SURVEY.md §7 build plan):
  utils/     — canonical codec, hashing, deterministic protocol RNG (L0)
  ops/       — JAX/TPU kernels + numpy references (L1)
  proof/     — ProofBackend seam: cpu / xla implementations (L2)
  chain/     — protocol state machines: sminer, storage-handler, file-bank,
               tee-worker, audit, scheduler-credit, oss, cacher, staking (L3)
               and the deterministic block loop / multi-role node sim (L4)
  parallel/  — device-mesh sharding of verification batches (L5)
"""

__version__ = "0.1.0"
