"""Flat G1 MSM sharded over a device mesh.

Σ_i [s_i]P_i is a bag of independent bucket accumulations plus one
final fold, so the mesh layout is pure lane sharding: every device runs
the Pippenger windowed-bucket kernel (ops/g1.py _msm_flat_kernel) over
its lane shard, and the per-device partial sums — one projective point
each — come back for a #devices-long host fold (point addition is not a
`psum`-able arithmetic op, and folding 8 partials host-side is O(1)).

This is the multi-chip shape of the batch-verification folds: the
σ-side Π σ_b^{ρ_b} of the combined PoDR2 check (proof/xla_backend.py)
and the signature-side fold of the aggregate BLS check (ops/bls_agg.py)
at BASELINE config-5 scale.  Bit-identity with the single-device flat
MSM (and the host fold) is asserted in tests/test_epoch_sim.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import g1
from ..ops.bls12_381 import G1Point
from .verify import BATCH_AXIS

_KERNEL_CACHE: dict = {}


def _sharded_kernel(mesh: Mesh, n_windows: int):
    key = (mesh, n_windows)
    fn = _KERNEL_CACHE.get(key)
    if fn is None:

        def local(X, Y, Z, d):
            rX, rY, rZ = g1._msm_flat_kernel(X, Y, Z, d, n_windows)
            return rX[None], rY[None], rZ[None]  # (1, L): this device's shard

        fn = jax.jit(
            shard_map(
                local,
                mesh=mesh,
                in_specs=(
                    P(None, BATCH_AXIS),
                    P(None, BATCH_AXIS),
                    P(None, BATCH_AXIS),
                    P(None, BATCH_AXIS),
                ),
                out_specs=(
                    P(BATCH_AXIS, None),
                    P(BATCH_AXIS, None),
                    P(BATCH_AXIS, None),
                ),
                check_rep=False,
            )
        )
        _KERNEL_CACHE[key] = fn
    return fn


def msm_sharded(
    mesh: Mesh,
    points: list[G1Point],
    scalars: list[int],
    bits: int = g1.SCALAR_BITS,
) -> G1Point:
    """Σ [s_i]P_i with the lane axis sharded over the mesh.  Scalars are
    raw integers up to `bits` wide (flat-MSM semantics: no reduction mod
    r — the cofactor-folding contract of ops/h2c.py)."""
    if len(points) != len(scalars):
        raise ValueError("points/scalars length mismatch")
    if not points:
        return G1Point.infinity()
    n_dev = mesh.devices.size
    n_windows = -(-bits // g1.LIMB_BITS)

    # pad the lane axis so every device holds the same number of lanes
    # (∞ with scalar 0 contributes nothing)
    pad = (-len(points)) % n_dev
    pts = list(points) + [G1Point.infinity()] * pad
    scs = [int(s) for s in scalars] + [0] * pad

    X, Y, Z = g1.points_to_projective(pts)  # (N, L)
    d = g1.scalars_to_digits(scs, n_windows)  # (n_windows, N)
    rX, rY, rZ = _sharded_kernel(mesh, n_windows)(
        jnp.asarray(X.T), jnp.asarray(Y.T), jnp.asarray(Z.T), jnp.asarray(d)
    )
    partials = g1.projective_to_points(
        np.asarray(rX), np.asarray(rY), np.asarray(rZ)
    )
    total = G1Point.infinity()
    for p in partials:
        total = total + p
    return total
