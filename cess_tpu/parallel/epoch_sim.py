"""Full-epoch multi-chip simulation (BASELINE config 5).

One storage-network epoch's device workload — "1M segments RS-recover +
100k proofs + BLS aggregate" — run end-to-end over a single
`jax.sharding.Mesh`:

  stage RS      every lost segment of the epoch is rebuilt from its
                surviving fragments: the GF(256) bitplane matmul
                (ops/rs.py) with the segment batch sharded over the mesh
                (embarrassingly parallel — no collectives; the
                restoral-order market's math, reference:
                c-pallets/file-bank/src/lib.rs:936-1125);

  stage AUDIT   the audit round's μ aggregation + ρ-weighted combination
                over the proof batch (parallel/verify.py: shard_map with
                the psum verdict reduction, reference seam:
                c-pallets/audit/src/lib.rs:484) plus the σ-side fold
                Π σ_b^{ρ_b} as a lane-sharded Pippenger MSM
                (parallel/msm.py);

  stage BLS     the epoch's TEE verdict signatures checked as ONE
                weighted batch (ops/bls_agg.py) with the signature-side
                fold sharded over the mesh (reference per-signature
                loop: utils/verify-bls-signatures/src/lib.rs:85-100);

  stage VRF     the epoch's header slot claims (cess_tpu/consensus:
                BLS-VRF proofs over (epoch randomness, slot)) verified
                as one batched pairing product — the catch-up /
                header-audit shape: an entire epoch of headers costs
                1 + #authors pairings instead of 2 per block.

  stage OFFENCE the epoch's accumulated equivocation evidence
                (chain/offences.py OffenceReport: two signatures over
                conflicting consensus payloads per report) swept in
                ONE weighted signature batch — 2N pairings collapse to
                1 + #offenders, the shape an era-boundary conviction
                pass would use to re-verify a backlog of reports —
                plus the host-side structural conflict checks.

Every stage is checked against host arithmetic when `check=True` (the
default — tests run tiny geometries on the virtual 8-device CPU mesh);
production-scale runs set check=False and read the timing breakdown.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

import numpy as np
from jax.sharding import Mesh

from ..ops import bls12_381 as bls
from ..ops import bls_agg, fr, g1, rs
from .msm import msm_sharded
from .verify import audit_data_plane_step


@dataclass
class EpochReport:
    n_devices: int
    segments: int
    rs_bytes: int
    rs_ok: bool
    proofs: int
    combine_ok: bool
    sigma_ok: bool
    signatures: int
    bls_ok: bool
    headers: int = 0
    vrf_ok: bool = True
    offences: int = 0
    offences_ok: bool = True
    seconds: dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return (self.rs_ok and self.combine_ok and self.sigma_ok
                and self.bls_ok and self.vrf_ok and self.offences_ok)


# ------------------------------------------------------------ epoch


def run_epoch(
    mesh: Mesh,
    *,
    n_segments: int = 64,
    fragment_bytes: int = 4096,
    n_proofs: int = 32,
    n_challenged: int = 5,
    n_sectors: int = 3,
    n_signatures: int = 8,
    n_keys: int = 2,
    n_headers: int = 64,
    n_validators: int = 3,
    n_offences: int = 8,
    seed: int = 7,
    check: bool = True,
    tracer=None,
) -> EpochReport:
    """Run one epoch's device workload over `mesh`.  All batch sizes are
    rounded up to multiples of the mesh size.  `tracer`
    (node/tracing.py Tracer) records one `epoch.run` trace with a span
    per stage, so dryrun epoch steps land in the same span-tree
    telemetry the live node emits."""
    n_dev = mesh.devices.size
    rnd = random.Random(seed)
    nprng = np.random.default_rng(seed)
    seconds: dict[str, float] = {}

    def r(n: int) -> int:
        return -(-n // n_dev) * n_dev

    n_segments, n_proofs = r(n_segments), r(n_proofs)
    n_signatures, n_headers = r(n_signatures), r(n_headers)

    # ---------------- stage RS: recover every segment from its survivors.
    # Segment i loses fragment i % 3 — MIXED per-segment erasure patterns,
    # grouped by survivor mask inside rs.RSStream (batch axis sharded
    # over the mesh, one fixed-slab executable shared by every group).
    code = rs.RSCode(2, 1, path="auto")
    data = nprng.integers(
        0, 256, size=(n_segments, 2, fragment_bytes), dtype=np.uint8
    )
    parity = np.asarray(code.encode_batch(data))
    allsh = np.concatenate([data, parity], axis=1)  # (B, 3, n)
    patterns = [sorted({0, 1, 2} - {i % 3}) for i in range(n_segments)]
    survivors = np.stack(
        [allsh[i, patterns[i]] for i in range(n_segments)]
    )
    slab = min(rs.SLAB, n_segments)
    rs.RSStream(  # compile: same (slab, k, n) geometry as the timed run
        code, present=patterns[:n_dev], mesh=mesh, slab=slab
    ).run_batch(survivors[:n_dev])
    t0 = time.perf_counter()
    recovered = rs.RSStream(
        code, present=patterns, mesh=mesh, slab=slab
    ).run_batch(survivors)
    seconds["rs"] = time.perf_counter() - t0
    rs_ok = bool(np.array_equal(recovered, data)) if check else True

    # ---------------- stage AUDIT: μ + combine (psum) + σ fold (sharded MSM)
    coeffs = [rnd.getrandbits(160) for _ in range(n_challenged)]
    sectors = [
        [
            [rnd.getrandbits(248) for _ in range(n_sectors)]
            for _ in range(n_challenged)
        ]
        for _ in range(n_proofs)
    ]
    rhos = [rnd.getrandbits(128) | 1 for _ in range(n_proofs)]
    step = audit_data_plane_step(mesh)
    v_limbs = fr.ints_to_limbs(coeffs, 23)
    sector_limbs = np.stack([fr.sectors_to_limbs(rows) for rows in sectors])
    rho_limbs = fr.ints_to_limbs(rhos, 19)
    step(v_limbs, sector_limbs[:n_dev], rho_limbs[:n_dev])  # compile
    t0 = time.perf_counter()
    _, combined = step(v_limbs, sector_limbs, rho_limbs)
    combined_ints = fr.limbs_to_ints(np.asarray(combined))
    seconds["audit_combine"] = time.perf_counter() - t0

    # σ points: distinct pseudorandom subgroup points (σ = [t]G — the
    # shape of real proof σ values; derivation cost is host-side setup,
    # not part of the timed device work)
    sigma_scalars = [rnd.getrandbits(250) for _ in range(n_proofs)]
    sigmas = g1.scalar_mul_batch(
        [bls.G1_GENERATOR] * n_proofs, sigma_scalars
    )
    t0 = time.perf_counter()
    sigma_fold = msm_sharded(mesh, sigmas, rhos, bits=128)
    seconds["sigma_fold"] = time.perf_counter() - t0

    combine_ok = sigma_ok = True
    if check:
        mus = [
            [
                sum(w * sectors[b][c][j] for c, w in enumerate(coeffs)) % fr.R
                for j in range(n_sectors)
            ]
            for b in range(n_proofs)
        ]
        want = [
            sum(rho * mus[b][j] for b, rho in enumerate(rhos)) % fr.R
            for j in range(n_sectors)
        ]
        combine_ok = combined_ints == want
        # host σ fold through the subgroup: Σ ρ_b·t_b mod r applied to G
        t_total = sum(rho * t for rho, t in zip(rhos, sigma_scalars)) % g1.R
        sigma_ok = sigma_fold == bls.G1_GENERATOR.mul(t_total)

    # ---------------- stage BLS: the epoch's verdict signatures, one batch
    keys = [bls.keygen(b"epoch-key-%d" % k) for k in range(n_keys)]
    pks = [bls.sk_to_pk(sk) for sk in keys]
    triples = []
    for i in range(n_signatures):
        k = i % n_keys
        msg = b"epoch-verdict-%d-%d" % (seed, i)
        triples.append((pks[k], msg, bls.sign(keys[k], msg)))
    t0 = time.perf_counter()
    bls_ok = bls_agg.batch_verify_signatures(
        triples, b"epoch-%d" % seed, mesh=mesh
    )
    seconds["bls_aggregate"] = time.perf_counter() - t0

    # ------------- stage VRF: the epoch's header slot claims, one batch
    from ..consensus import vrf as _vrf

    vkeys = [bls.keygen(b"epoch-author-%d" % k) for k in range(n_validators)]
    vpks = [bls.sk_to_pk(sk) for sk in vkeys]
    epoch_rand = b"%032d" % seed
    claims = []
    for slot in range(n_headers):
        k = slot % n_validators
        msg = _vrf.vrf_input("epoch-sim", 1, epoch_rand, slot)
        out, proof = _vrf.prove(vkeys[k], msg)
        claims.append((vpks[k], msg, out, proof))
    t0 = time.perf_counter()
    vrf_ok = _vrf.batch_verify(claims, b"epoch-%d" % seed, mesh=mesh)
    seconds["vrf_headers"] = time.perf_counter() - t0
    if check:
        vrf_ok = vrf_ok and all(
            _vrf.verify(*claims[i]) for i in (0, n_headers - 1)
        )

    # ---------- stage OFFENCE: the era's equivocation evidence, one batch
    from ..chain import offences as _off

    n_offences = r(n_offences)
    off_triples = []
    offences_ok = True
    for i in range(n_offences):
        k = i % n_validators
        sk, pk = vkeys[k], vpks[k]
        # two conflicting finality payloads (same height, different
        # hash) signed by the same offender — the OffenceReport shape
        p1 = b'["epoch-sim","finality",%d,"aa%02x"]' % (i, i & 0xFF)
        p2 = b'["epoch-sim","finality",%d,"bb%02x"]' % (i, i & 0xFF)
        offences_ok = offences_ok and p1 != p2  # structural conflict
        off_triples.append((pk, p1, bls.sign(sk, p1)))
        off_triples.append((pk, p2, bls.sign(sk, p2)))
    t0 = time.perf_counter()
    if off_triples:
        offences_ok = offences_ok and bls_agg.batch_verify_signatures(
            off_triples, b"offences-%d" % seed, mesh=mesh
        )
    seconds["offence_sweep"] = time.perf_counter() - t0
    if check and n_offences:
        # one report must also survive the pallet's full structural
        # verifier (host path) — the batch and the per-report gate
        # must agree
        rep = _off.OffenceReport(
            kind=_off.KIND_VOTE_EQUIV, offender="v0", session=0,
            evidence=[
                [off_triples[0][1].hex(), off_triples[0][2].hex()],
                [off_triples[1][1].hex(), off_triples[1][2].hex()],
            ],
        )
        offences_ok = offences_ok and _off.verify_report(
            rep, "epoch-sim", {"v0": vpks[0]}.get
        )

    if tracer is not None:
        with tracer.span(
            "epoch.run", tags={"devices": n_dev, "proofs": n_proofs}
        ) as root:
            for stage, dur in seconds.items():
                tracer.event(f"epoch.{stage}", duration=dur)
        # the stages ran before the span opened: back-date the root's
        # duration to the measured epoch wall-clock (the ring holds
        # the same Span object, so post-exit mutation is visible)
        root.duration = sum(seconds.values())

    return EpochReport(
        n_devices=n_dev,
        segments=n_segments,
        rs_bytes=n_segments * 2 * fragment_bytes,
        rs_ok=rs_ok,
        proofs=n_proofs,
        combine_ok=combine_ok,
        sigma_ok=sigma_ok,
        signatures=n_signatures,
        bls_ok=bls_ok,
        headers=n_headers,
        vrf_ok=vrf_ok,
        offences=n_offences,
        offences_ok=offences_ok,
        seconds=seconds,
    )
