"""Device-mesh scale-out (SURVEY.md §7 L5).

The reference's scaling axes are validator-parallel challenge work with
quorum aggregation and hash-scattered verification queues (SURVEY.md §2
"parallelism strategies") over libp2p.  The TPU-native equivalents here
shard the audit round's proof batch across a `jax.sharding.Mesh` with
`shard_map`, reducing verdict material with XLA collectives (`psum`) over
ICI — the role NCCL/MPI would play in a GPU framework, with no host-side
gather in the loop.
"""

from .verify import (
    audit_data_plane_step,
    combine_mu_sharded,
    make_mesh,
    pad_batch_rows,
)
from .msm import msm_sharded
from .epoch_sim import EpochReport, run_epoch

__all__ = [
    "audit_data_plane_step",
    "combine_mu_sharded",
    "make_mesh",
    "msm_sharded",
    "pad_batch_rows",
    "run_epoch",
    "EpochReport",
]
