"""Sharded audit-round data plane: shard_map over the proof batch.

One audit round's device work (xla ProofBackend, cess_tpu/proof/xla_backend)
at multi-chip scale:

  stage 1 (μ):       every proof's μ_j = Σ_c v_c·m_{c,j} — batch-sharded,
                     embarrassingly parallel, no collectives;
  stage 2 (combine): e_j = Σ_b ρ_b·μ_{b,j} — each device combines its local
                     batch shard, then one `psum` over the mesh adds the
                     per-device partial limb vectors (the verdict-aggregate
                     reduction; the analog of the reference's 2/3-quorum
                     aggregation of identical challenge votes, reference:
                     c-pallets/audit/src/lib.rs:380-399).

The psum'd partials are re-canonicalized on device, so the sharded result is
bit-identical to the single-device kernel — asserted in tests on a virtual
8-device CPU mesh.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import mesh_utils
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import fr

BATCH_AXIS = "proofs"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the proof-batch axis.  Verification is a bag of
    independent proofs + one reduction, so the natural layout is pure batch
    ("dp-like") sharding with the reduction riding ICI."""
    devices = jax.devices()
    n = n_devices or len(devices)
    mesh_devices = mesh_utils.create_device_mesh((n,), devices=devices[:n])
    return Mesh(mesh_devices, (BATCH_AXIS,))


def pad_batch_rows(arr: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad the leading (batch) axis up to a multiple — the host
    staging step every sharded entry point needs (ρ=0 / μ=0 rows are
    combine-inert, so the padded result is bit-identical)."""
    pad = (-arr.shape[0]) % multiple
    if not pad:
        return arr
    return np.concatenate(
        [arr, np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)]
    )


def _combine_local(w: jnp.ndarray, mu: jnp.ndarray) -> jnp.ndarray:
    """Local shard combine + cross-device sum + re-canonicalize."""
    part = fr.weighted_sum_kernel(w, jnp.moveaxis(mu, 0, -2))  # (S, 37)
    total = jax.lax.psum(part, BATCH_AXIS)  # limbs ≤ 127 · n_devices
    total = fr._normalize(
        jnp.pad(total, [(0, 0)] * (total.ndim - 1) + [(0, 3)])
    )
    return fr._fold_to_canonical(total)


@lru_cache(maxsize=8)
def _combine_fn(mesh: Mesh):
    """Jitted sharded combine, cached per mesh — building the jit per
    call re-traced the shard_map on every audit round (the glv bug
    class; caught by cesslint jit-in-body)."""
    fn = shard_map(
        _combine_local,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS, None), P(BATCH_AXIS, None, None)),
        out_specs=P(None, None),
        check_rep=False,
    )
    return jax.jit(fn)


def combine_mu_sharded(
    mesh: Mesh, rho_limbs: np.ndarray, mu_limbs: np.ndarray
) -> np.ndarray:
    """Σ_b ρ_b·μ_b mod r with the batch axis sharded over the mesh.

    rho_limbs: (B, Lw) int8;  mu_limbs: (B, S, Lm) int8.
    B must divide by mesh size (pad with ρ=0 rows host-side).
    Returns (S, NLIMBS) canonical int32 limbs, identical on every device.
    """
    return np.asarray(
        _combine_fn(mesh)(jnp.asarray(rho_limbs), jnp.asarray(mu_limbs))
    )


def _audit_step_local(
    v: jnp.ndarray, sectors: jnp.ndarray, rho: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One device's full audit data-plane step over its batch shard:
    μ for each local proof, then the psum'd batch combination."""
    # sectors: (b_local, C, S, Lm) → μ (b_local, S, 37)
    mu = fr.weighted_sum_kernel(v, jnp.moveaxis(sectors, 1, -2))
    # combine: contract local batch with local ρ then psum partials.
    # canonical limbs are strictly < 128 (fr._fold_to_canonical ends with
    # an exact carry) ⇒ the int8 recast is lossless.
    mu8 = mu.astype(jnp.int8)
    part = fr.weighted_sum_kernel(rho, jnp.moveaxis(mu8, 0, -2))  # (S, 37)
    total = jax.lax.psum(part, BATCH_AXIS)
    total = fr._normalize(
        jnp.pad(total, [(0, 0)] * (total.ndim - 1) + [(0, 3)])
    )
    return mu, fr._fold_to_canonical(total)


def audit_data_plane_step(mesh: Mesh):
    """Build the jitted multi-chip audit step.

    Returns fn(v_limbs (C, Lv), sector_limbs (B, C, S, Lm) [sharded on B],
    rho_limbs (B, Lw) [sharded on B]) → (μ (B, S, 37) [sharded on B],
    combined (S, 37) [replicated]).
    """
    fn = shard_map(
        _audit_step_local,
        mesh=mesh,
        in_specs=(
            P(None, None),
            P(BATCH_AXIS, None, None, None),
            P(BATCH_AXIS, None),
        ),
        out_specs=(P(BATCH_AXIS, None, None), P(None, None)),
        check_rep=False,
    )
    return jax.jit(fn)
