"""XLA ProofBackend — the TPU data-plane path.

Work split (SURVEY.md §2 "distributed communication backend" item — keep the
hot data plane on device, control on host):

 * μ aggregation over challenged sectors (prove) and the ρ-weighted batch
   combination (verify) run on TPU as base-128 limb matmuls
   (ops/fr.py) — this is where the bytes are: for the north-star batch the
   sector data is GiBs while the G1 points are KiBs.
 * G1 MSMs and the two pairings run host-side via ops/bls12_381.py until
   the ops/g1.py device kernels land (round-2 frontier).

Verdicts are bit-identical to CpuBackend: the combined equation uses the
same ρ derivation (ops/podr2.py batch_rho) and the device μ math is
bit-identical to Python mod-r arithmetic (tests/test_fr.py).
"""

from __future__ import annotations

import numpy as np

from ..ops import fr, podr2
from ..ops.bls12_381 import G1Point, R
from ..ops.podr2 import Challenge, Podr2Params, Podr2Proof
from .backend import ProofBackend, ProveRequest, VerifyItem

# Fragment-axis chunk for prove_batch: bounds host staging + HBM footprint
# (47×265×36 limb bytes ≈ 448 KB per fragment).
_PROVE_CHUNK = 1024


class XlaBackend(ProofBackend):
    name = "xla"

    # ------------------------------------------------------------ verify

    def _combined_check(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
    ) -> bool:
        """ops/podr2.py batch_verify with the u-side exponents
        Σ_b ρ_b μ_bj computed on device — the only seam where this backend
        differs from the host reference."""
        if not items:
            return True
        batch_items = [podr2.BatchItem(n, c, p) for n, c, p in items]
        if any(len(p.mu) != params.s for _, _, p in items):
            return False
        if any(not 0 <= m < R for _, _, p in items for m in p.mu):
            return False
        rhos = podr2.batch_rho(
            podr2.batch_transcript(seed, batch_items), len(items)
        )
        mu_limbs = np.stack(
            [fr.fr_to_limbs(p.mu) for _, _, p in items]
        )  # (B, S, 37)
        exps = fr.limbs_to_ints(fr.combine_mu(rhos, mu_limbs))
        return podr2.batch_verify(
            pk, batch_items, seed, u_exponents=exps, s=params.s
        )

    def verify_batch(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
    ) -> list[bool]:
        def single_check(pk_, item, params_):
            name, challenge, proof = item
            return podr2.verify(pk_, name, challenge, proof, s=params_.s)

        return self._verdicts_by_bisection(
            pk, items, seed, params, self._combined_check, single_check
        )

    # ------------------------------------------------------------ prove

    def prove_batch(self, request: ProveRequest) -> list[Podr2Proof]:
        """μ on device (challenged sectors only — 47/1024 of the data moves
        to HBM), σ host-side MSM over the 47 challenged tags."""
        params = request.params
        challenge = request.challenge
        coeffs = challenge.coefficients()

        proofs: list[Podr2Proof] = []
        for start in range(0, len(request.data), _PROVE_CHUNK):
            chunk_data = request.data[start : start + _PROVE_CHUNK]
            chunk_tags = request.tags[start : start + _PROVE_CHUNK]
            # Challenged rows only — 47/1024 of the fragment bytes move.
            batches = []
            for data in chunk_data:
                matrix = podr2.fragment_sectors(data, params)
                rows = [matrix[i] for i in challenge.indices]
                batches.append(fr.sectors_to_limbs(rows))
            sector_limbs = np.stack(batches)
            mu_all = fr.mu_aggregate(coeffs, sector_limbs)  # (n, S, 37)

            for b, tags in enumerate(chunk_tags):
                mu = fr.limbs_to_ints(mu_all[b])
                sigma = G1Point.infinity()
                for v, i in zip(coeffs, challenge.indices):
                    sigma = sigma + G1Point.from_bytes(tags[i]).mul(v)
                proofs.append(Podr2Proof(sigma.to_bytes(), mu))
        return proofs
