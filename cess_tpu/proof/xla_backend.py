"""XLA ProofBackend — the TPU data-plane path.

Work split (SURVEY.md §2 "distributed communication backend" item — keep
the hot data plane on device, control on host):

 * μ aggregation over challenged sectors (prove) and the ρ-weighted batch
   combination (verify) run on TPU as base-128 limb matmuls (ops/fr.py).
 * Every G1 multi-scalar multiplication — the verify equation's σ^ρ fold,
   its H/u products, and the prover's σ fold — runs on TPU through the
   complete-formula limb kernels in ops/g1.py.
 * Only the two pairings per combined check (O(1) per batch) and the
   hash-to-curve points stay host-side (ops/bls12_381.py).

Verdicts are bit-identical to CpuBackend: the combined equation uses the
same ρ derivation (ops/podr2.py batch_rho) and the device group math is
bit-identical to the host fold (tests/test_g1.py); the H-side product is
associated as Π_b (Π_c H_{b,c}^{v_c})^{ρ_b}, the same group element as
the host's flat Π_{b,c} H_{b,c}^{ρ_b v_c}.

Capability match: the reference's pairing-side verify
(utils/verify-bls-signatures/src/lib.rs:85-100) and the audit pallet's
declared verification seam (c-pallets/audit/src/lib.rs:484).
"""

from __future__ import annotations

import os
import threading
import time as _time

import jax
import numpy as np

from ..ops import bls12_381 as bls
from ..ops import fr, g1, h2c, podr2
from ..ops.bls12_381 import G1Point, G2Point
from ..ops.podr2 import Challenge, Podr2Params, Podr2Proof
from .backend import ProofBackend, ProveRequest, VerifyItem

# Fragment-axis chunk for prove_batch: bounds host staging + HBM footprint
# (47×265×36 limb bytes ≈ 448 KB per fragment).
_PROVE_CHUNK = 1024

# Challenge coefficients are 20-byte randoms (audit/src/lib.rs:916-924);
# batch weights ρ are 128-bit by construction (podr2.batch_rho).
_COEFF_BITS = 160
_RHO_BITS = 128
# Coefficients arrive at the MSM multiplied by the effective cofactor
# (ops/h2c.py cofactor-folding contract): 160 + 64 bits.
_COEFF_HEFF_BITS = _COEFF_BITS + 64

# Below this many (proof, chunk) pairs the host native hash-to-curve
# (native/blsmap.cpp, ~0.6 ms/pair) beats paying a device map compile +
# padded launch; above it the device SSWU path (ops/h2c.py) wins and
# scales.  Verdicts are bit-identical either way (tests/test_h2c.py).
_DEVICE_H2C_MIN_PAIRS = 256


# ------------------------------------------------------- stage telemetry
#
# Always-on per-stage histograms of _combined_check (the promotion of
# the opt-in profile_stages breakdown — ROADMAP item 1 needs per-stage
# timing that survives outside bench.py).  They live in a process-wide
# registry of their own so any host embedding a backend (node RPC,
# TEE client, bench) exposes them without threading a registry through
# the proof API; the node's `system_metrics` merges this registry into
# its exposition (node/rpc.py).
#
# BOTH verify pipelines observe the same stage names: the staged path
# below marks host_prep/u_fold/sigma_fold/chunk_program/pairing, and
# the fused single-program path (proof/fused.py combined_check_fused)
# marks host_prep/chunk_program/u_fold/pairing plus `dispatch_wait` —
# the block on device results after every chunk is in flight, i.e. the
# device time the double-buffered host prep failed to hide (σ work is
# inside the fused chunk program, so sigma_fold has no fused
# observations).  docs/perf.md explains how to read the split.
#
# Overhead guard: each stage below already ends in a host
# materialization, so a mark is ONE perf_counter call plus one locked
# histogram observe — single-digit microseconds against stages that
# cost milliseconds.  tests/test_telemetry.py measures the mark cost,
# and bench.py's marginal ms/proof is the end-to-end check (< 2%
# budget).  CESS_STAGE_METRICS=0 switches the marks off entirely for
# A/B measurement.

STAGE_NAMES = ("host_prep", "u_fold", "sigma_fold", "chunk_program",
               "dispatch_wait", "pairing")
STAGE_METRICS_ENABLED = os.environ.get(
    "CESS_STAGE_METRICS", "1") not in ("0", "false", "off")

_stage_lock = threading.Lock()
_stage_registry = None
_stage_hists: dict = {}
_stage_counters: dict = {}

_STAGE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                  0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)


def proof_stage_registry():
    """The process-wide metrics registry for the proof data plane
    (created on first use; node/metrics is imported lazily to keep the
    proof↔node package import graph acyclic)."""
    global _stage_registry
    with _stage_lock:
        if _stage_registry is None:
            from ..node import metrics as m

            reg = m.Registry()
            for name in STAGE_NAMES:
                _stage_hists[name] = m.Histogram(
                    f"cess_proof_stage_{name}_seconds",
                    f"combined-check {name} stage time",
                    buckets=_STAGE_BUCKETS, registry=reg)
            _stage_counters["proofs"] = m.Counter(
                "cess_proofs_verified",
                "proof items covered by combined checks", reg)
            _stage_counters["checks"] = m.Counter(
                "cess_proof_checks",
                "combined pairing checks executed", reg)
            _stage_counters["seconds"] = m.Counter(
                "cess_proof_verify_seconds_total",
                "wall-clock seconds spent in combined checks", reg)
            _stage_registry = reg
    return _stage_registry


def _observe_stage(name: str, seconds: float) -> None:
    proof_stage_registry()
    _stage_hists[name].observe(seconds)


def _subgroup_ok(points, device: bool | None = None) -> bool:
    """True iff every point is in the r-order subgroup (or ∞) — the
    shared deferred-subgroup gate behind g1_decompress_batch(
    check_subgroup=False) on the staged verify and prove paths.

    device=None is auto: ONE batched device [r]-chain (ops/glv.py
    subgroup_mask) on a real TPU, where the whole batch costs
    microseconds per point; the per-point host ladder on CPU hosts,
    where the emulated chain measured ~3× SLOWER than the ladder
    (10.7 vs 3.3 ms/point at 1024 lanes) — the same auto shape as
    device_h2c.  CESS_DEVICE_SUBGROUP=1/0 forces either way (tests
    force the device wiring on the CPU mesh).  Both routes are
    bit-identical (tests/test_fused TestGlv subgroup_mask matrix)."""
    if not points:
        return True
    if device is None:
        env = os.environ.get("CESS_DEVICE_SUBGROUP")
        if env is not None:
            device = env not in ("0", "false", "off")
        else:
            device = jax.default_backend() == "tpu"
    if not device:
        return all(p.in_subgroup() for p in points)
    import jax.numpy as jnp

    from ..ops import glv
    from ..ops.bls12_381 import G1Point as _G1
    from .fused import pack_points_limbs

    # pow2 ∞-pad with an 8-lane floor: tiny batches (single-proof
    # bisection leaves, 1-item RPC verifies) share one compiled mask
    # shape instead of one per batch size
    m = max(8, 1 << max(0, (len(points) - 1).bit_length()))
    X, Y, Z = pack_points_limbs(
        list(points) + [_G1.infinity()] * (m - len(points))
    )
    mask = np.asarray(
        glv.subgroup_mask(jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    )
    return bool(np.all(mask == 1))


class XlaBackend(ProofBackend):
    """mesh: optional jax.sharding.Mesh over the proof-batch axis.  When
    given, the ρ-weighted μ combination runs through the sharded data
    plane (parallel/verify.py: shard_map + psum over ICI) instead of the
    single-device kernel — bit-identical verdicts either way
    (tests/test_parallel.py)."""

    name = "xla"

    def __init__(
        self,
        mesh=None,
        device_h2c: bool | None = None,
        fused: bool | None = None,
        profile_stages: bool = False,
    ) -> None:
        self.mesh = mesh
        # profile_stages: accumulate a per-stage wall-clock breakdown of
        # _combined_check into `stage_seconds` (host prep / u fold /
        # σ fold / chunk program / pairing).  Each boundary blocks on
        # the stage's device values so the split is real — run it on a
        # SEPARATE pass, never the timed one (bench.py does exactly
        # that; the blocking serializes the dispatch pipeline).
        self.profile_stages = profile_stages
        self.stage_seconds: dict[str, float] = {}
        # fused: None = auto (the single-program GLV pipeline of
        # proof/fused.py on a real TPU); True/False force it — tests
        # force True to exercise the fused path on the CPU mesh.
        # Verdicts are bit-identical either way (tests/test_fused.py).
        # The fused pipeline is single-device: forcing it alongside a
        # mesh would silently ignore the sharded data plane the caller
        # asked for, so the combination is rejected outright.
        if fused and mesh is not None:
            raise ValueError(
                "fused=True is single-device and incompatible with a "
                "mesh; use fused=None/False on meshed backends"
            )
        self.fused = fused
        # device_h2c: None = auto (device SSWU only on a real TPU, where
        # the fused Pallas map wins); True/False force it — tests force
        # True to exercise the wiring on the CPU mesh.  On CPU the
        # emulated-limb map is slower than the native host hash, so auto
        # keeps CPU-only hosts on the native path at every batch size.
        self.device_h2c = device_h2c
        # H-point memo for one verify_batch call: the bisection tree
        # re-visits identical (name, index) pairs across overlapping
        # subsets; hash each pair once (the cached-chunk_point role of
        # the host path, scoped to the call so memory stays bounded).
        self._h_memo: dict[tuple[bytes, int], object] = {}

    def _chunk_points(self, pairs: list[tuple[bytes, int]]) -> list:
        missing = [p for p in pairs if p not in self._h_memo]
        if missing:
            for p, pt in zip(missing, podr2.chunk_points_batch(missing)):
                self._h_memo[p] = pt
        return [self._h_memo[p] for p in pairs]

    # ------------------------------------------------------------ verify

    def _h_inner_fold_device(self, items: list[VerifyItem]) -> list[G1Point]:
        """Per-item Π_c H(name‖i_c)^{v_c}, entirely on device: host XMD →
        device SSWU map (uncleared points) → grouped MSM with v_c·h_eff
        scalars ([v·h_eff]Q = [v]([h_eff]Q), so the result is the cleared
        fold — tests/test_h2c.py TestCofactorFolding)."""
        import jax.numpy as jnp

        B = len(items)
        names = [name for name, _, _ in items]
        # zip-truncation semantics, exactly like the host reference's
        # `zip(coefficients(), indices)` (ops/podr2.py _rhs_point /
        # batch_verify): a challenge with mismatched index/random list
        # lengths contributes min(len) pairs on every backend.
        counts = [
            min(len(ch.indices), len(ch.randoms)) for _, ch, _ in items
        ]
        name_ids = np.repeat(np.arange(B, dtype=np.uint32), counts)
        indices = np.concatenate(
            [
                np.asarray(ch.indices[:c], dtype=np.uint64)
                for (_, ch, _), c in zip(items, counts)
            ]
        )
        (X, Y, Z), n = h2c.hash_pairs_device(
            names, name_ids, indices, podr2.H_DST
        )

        # grouped layout: pad each item's chunk row to a power-of-two
        # width, and the item count to a power of two (dead lanes get
        # scalar 0, which the ladder turns into an ∞ contribution
        # regardless of the gathered point).
        g = 1 << max(0, (max(counts) - 1).bit_length())
        Bp = 1 << max(0, (B - 1).bit_length())
        lane_map = np.zeros((Bp, g), dtype=np.int32)
        slimbs = np.zeros((Bp, g, g1.R_LIMBS), dtype=np.int32)
        limb_cache: dict[int, np.ndarray] = {}

        def limbs_of(v: int) -> np.ndarray:
            row = limb_cache.get(v)
            if row is None:
                row = g1.scalars_to_digits([v], g1.R_LIMBS)[:, 0]
                limb_cache[v] = row
            return row

        pos = 0
        for b, ((_, ch, _), cnt) in enumerate(zip(items, counts)):
            coeffs = ch.coefficients()[:cnt]
            for k, v in enumerate(coeffs):
                lane_map[b, k] = pos + k
                slimbs[b, k] = limbs_of(v * h2c.H_EFF)
            pos += cnt

        flat = lane_map.reshape(-1)
        Xg = jnp.take(X, jnp.asarray(flat), axis=1)
        Yg = jnp.take(Y, jnp.asarray(flat), axis=1)
        Zg = jnp.take(Z, jnp.asarray(flat), axis=1)
        s = jnp.asarray(slimbs.reshape(Bp * g, g1.R_LIMBS).T)
        rX, rY, rZ = g1._msm_kernel(
            Xg, Yg, Zg, s, bits=_COEFF_HEFF_BITS, group=g
        )
        return g1.projective_to_points(
            np.asarray(rX).T[:B], np.asarray(rY).T[:B], np.asarray(rZ).T[:B]
        )

    def _combined_check(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
    ) -> bool:
        """One pairing equation for the whole batch, with every group fold
        on device:

          e(Π_b σ_b^{ρ_b}, −g2) · e(Π_b (Π_c H_{b,c}^{v_c})^{ρ_b}
                                     · Π_j u_j^{Σ_b ρ_b μ_bj}, pk) == 1
        """
        if not items:
            return True
        use_fused = (
            self.fused
            if self.fused is not None
            else jax.default_backend() == "tpu"
        ) and self.mesh is None
        if use_fused:
            from .fused import combined_check_fused

            return combined_check_fused(
                pk, items, seed, params,
                stages=self.stage_seconds if self.profile_stages else None,
            )
        from . import frontend

        stages = self.stage_seconds if self.profile_stages else None
        metered = STAGE_METRICS_ENABLED

        def mark(name, t0):
            """Stage boundary: charge the elapsed wall clock to `name`.
            Honest because every stage below ends in host
            materialization (g1.msm / limbs_to_ints return host
            values, pairing_check is host) — a stage changed to return
            a device-resident array must add its own block_until_ready
            here or its cost silently migrates to the next bucket.
            Always on: the per-stage histograms (proof_stage_registry)
            observe every combined check; `profile_stages` additionally
            accumulates the per-backend stage_seconds dict bench.py
            logs.  One perf_counter + one locked observe per stage —
            the measured-overhead guard in tests/test_telemetry.py."""
            if not metered and stages is None:
                return t0
            now = _time.perf_counter()
            if stages is not None:
                stages[name] = stages.get(name, 0.0) + (now - t0)
            if metered:
                _observe_stage(name, now - t0)
            return now

        # The whole front-end sits AFTER check_t0 so host_prep means
        # the same thing on both pipelines (the fused path charges its
        # front-end to host_prep too — bench/profile breakdowns are
        # compared side by side).  Early rejections return before any
        # mark, exactly like the fused path's.
        check_t0 = _time.perf_counter()
        t0 = check_t0
        try:
            pk_point = G2Point.from_bytes(pk)
        except ValueError:
            return False
        # batched decompression with the subgroup test deferred: the
        # per-σ host ladder (~3 ms each) becomes ONE device [r]-chain
        # over the whole batch below — same rejection set
        # (tests/test_proof_backends.py non-subgroup/tampered matrix).
        sigmas = frontend.decompress_sigmas(items)
        if sigmas is None:
            return False
        if any(len(p.mu) != params.s for _, _, p in items):
            return False
        encs = frontend.encode_proofs(items)
        if encs is None:
            return False
        words = frontend.mu_words(encs, params.s)
        if not frontend.mu_in_range(words):
            return False
        batch_items = [podr2.BatchItem(n, c, p) for n, c, p in items]
        rhos = podr2.batch_rho(
            podr2.batch_transcript(seed, batch_items, encodings=encs),
            len(items),
        )
        # μ limbs come from the SAME encode pass as the transcript — a
        # numpy word unpack instead of B·S per-limb Python loops.
        mu_limbs = frontend.mu_limbs(words)  # (B, S, 37)
        t0 = mark("host_prep", t0)

        # σ subgroup gate: the test deferred from decompression runs as
        # one batched device [r]-chain (ops/glv.py subgroup_mask —
        # bit-identical to the host in_subgroup ladder, tests/test_fused
        # TestGlv), ∞-padded to a power of two ([r]∞ = ∞ passes).
        sub_ok = _subgroup_ok(sigmas)
        t0 = mark("sigma_fold", t0)
        if not sub_ok:
            return False

        # u-side exponents Σ_b ρ_b μ_bj: device limb matmul (ops/fr.py) —
        # sharded over the mesh when one is configured (ρ=0 row padding
        # contributes nothing to the combination).
        if self.mesh is not None:
            from ..parallel import combine_mu_sharded, pad_batch_rows

            n_dev = self.mesh.devices.size
            rho_limbs = pad_batch_rows(
                frontend.rho_limbs7(rhos), n_dev
            )
            mu_limbs = pad_batch_rows(mu_limbs, n_dev)
            exps = fr.limbs_to_ints(
                combine_mu_sharded(self.mesh, rho_limbs, mu_limbs)
            )
        else:
            exps = fr.limbs_to_ints(fr.combine_mu(rhos, mu_limbs))
        t0 = mark("u_fold", t0)

        # σ-side: Π σ_b^{ρ_b} — one flat MSM over the batch.
        lhs = g1.msm(sigmas, rhos, bits=_RHO_BITS)
        t0 = mark("sigma_fold", t0)

        # H-side: per-item Π_c H^{v_c} (grouped MSM over the challenged
        # chunk points), then the ρ fold across items.  At batch scale
        # the random-oracle points are hashed ON DEVICE (ops/h2c.py:
        # host XMD → device SSWU) and stay device-resident into the MSM,
        # with the effective cofactor folded into the coefficients.
        n_pairs = sum(len(ch.indices) for _, ch, _ in items)
        use_device = (
            self.device_h2c
            if self.device_h2c is not None
            else jax.default_backend() == "tpu"
        )
        if use_device and n_pairs >= _DEVICE_H2C_MIN_PAIRS:
            inner = self._h_inner_fold_device(items)
        else:
            # same zip-truncation semantics as the host reference and
            # the device branch above
            counts = [
                min(len(ch.indices), len(ch.randoms)) for _, ch, _ in items
            ]
            flat_pairs = [
                (name, i)
                for (name, ch, _), c in zip(items, counts)
                for i in ch.indices[:c]
            ]
            flat_pts = self._chunk_points(flat_pairs)
            h_pts = []
            pos = 0
            for c in counts:
                h_pts.append(flat_pts[pos : pos + c])
                pos += c
            h_coeffs = [
                list(ch.coefficients()[:c])
                for (_, ch, _), c in zip(items, counts)
            ]
            inner = g1.msm_grouped(h_pts, h_coeffs, bits=_COEFF_BITS)
        rhs = g1.msm(inner, rhos, bits=_RHO_BITS)
        t0 = mark("chunk_program", t0)

        # u-side: Π_j u_j^{e_j} over the global sector generators.
        us = list(podr2.u_generators(params.s))
        rhs = rhs + g1.msm(us, exps)
        t0 = mark("u_fold", t0)

        verdict = bls.pairing_check(
            [(lhs, -bls.G2_GENERATOR), (rhs, pk_point)]
        )
        mark("pairing", t0)
        if metered:
            proof_stage_registry()
            _stage_counters["checks"].inc()
            _stage_counters["proofs"].inc(len(items))
            _stage_counters["seconds"].inc(
                _time.perf_counter() - check_t0)
        return verdict

    def verify_batch(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
    ) -> list[bool]:
        def single_check(pk_, item, params_):
            name, challenge, proof = item
            return podr2.verify(pk_, name, challenge, proof, s=params_.s)

        self._h_memo = {}
        try:
            return self._verdicts_by_bisection(
                pk, items, seed, params, self._combined_check, single_check
            )
        finally:
            self._h_memo = {}

    # ------------------------------------------------------------ prove

    def prove_batch(self, request: ProveRequest) -> list[Podr2Proof]:
        """μ on device (challenged sectors only — 47/1024 of the data moves
        to HBM); σ = Π_c tag_{i_c}^{v_c} per fragment as one grouped MSM.
        Tag decompression is batched (ops/bls12_381.g1_decompress_batch)
        with the subgroup test deferred to one device [r]-chain per chunk
        — the per-tag host ladder cost ~3 ms × 47 tags × fragment; the
        rejection set (ValueError on any malformed or non-subgroup tag)
        matches the host reference's per-tag from_bytes."""
        params = request.params
        challenge = request.challenge
        coeffs = challenge.coefficients()

        proofs: list[Podr2Proof] = []
        for start in range(0, len(request.data), _PROVE_CHUNK):
            chunk_data = request.data[start : start + _PROVE_CHUNK]
            chunk_tags = request.tags[start : start + _PROVE_CHUNK]
            # Challenged rows only — 47/1024 of the fragment bytes move.
            batches = []
            for data in chunk_data:
                matrix = podr2.fragment_sectors(data, params)
                rows = [matrix[i] for i in challenge.indices]
                batches.append(fr.sectors_to_limbs(rows))
            sector_limbs = np.stack(batches)
            mu_all = fr.mu_aggregate(coeffs, sector_limbs)  # (n, S, 37)

            flat = bls.g1_decompress_batch(
                [tags[i] for tags in chunk_tags for i in challenge.indices],
                check_subgroup=False,
            )
            self._require_subgroup(flat)
            k = len(challenge.indices)
            tag_pts = [
                flat[b * k : (b + 1) * k] for b in range(len(chunk_tags))
            ]
            sigmas = g1.msm_grouped(
                tag_pts,
                [list(coeffs)] * len(tag_pts),
                bits=_COEFF_BITS,
            )
            for b, sigma in enumerate(sigmas):
                mu = fr.limbs_to_ints(mu_all[b])
                proofs.append(Podr2Proof(sigma.to_bytes(), mu))
        return proofs

    @staticmethod
    def _require_subgroup(points: list[G1Point]) -> None:
        """Raises the scalar path's 'point not in G1 subgroup'
        ValueError if any point fails the batched device check."""
        if points and not _subgroup_ok(points):
            raise ValueError("point not in G1 subgroup")
