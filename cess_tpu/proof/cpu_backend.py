"""CPU reference ProofBackend — the bit-exactness anchor.

Pure host Python over ops/podr2.py + ops/bls12_381.py.  Mirrors the role of
the reference's in-TEE Rust verifier (capability surface: reference
primitives/enclave-verify/src/lib.rs:230-235 verify_bls and the audit seam
at c-pallets/audit/src/lib.rs:484).
"""

from __future__ import annotations

from ..ops import podr2
from ..ops.podr2 import BatchItem, Podr2Params, Podr2Proof
from .backend import ProofBackend, ProveRequest, VerifyItem


class CpuBackend(ProofBackend):
    name = "cpu"

    def verify_batch(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
    ) -> list[bool]:
        def batch_check(pk_, subset, seed_, params_):
            return podr2.batch_verify(
                pk_,
                [BatchItem(n, c, p) for n, c, p in subset],
                seed_,
                s=params_.s,
            )

        def single_check(pk_, item, params_):
            name, challenge, proof = item
            return podr2.verify(pk_, name, challenge, proof, s=params_.s)

        return self._verdicts_by_bisection(
            pk, items, seed, params, batch_check, single_check
        )

    def prove_batch(self, request: ProveRequest) -> list[Podr2Proof]:
        return [
            podr2.prove(tags, data, request.challenge, request.params)
            for tags, data in zip(request.tags, request.data)
        ]
