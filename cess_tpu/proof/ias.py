"""IAS attestation-report verification — the enclave-verify equivalent.

Re-expresses the capability of the reference's `verify_miner_cert`
(reference: primitives/enclave-verify/src/lib.rs:135-219): base64-decode
the attached signing certificate, validate it against a pinned root set
at a FIXED verification time, then check the RSA-PKCS1-SHA256 signature
of the raw report JSON with the certificate's public key.  The X.509/DER
work (the vendored-webpki role, reference: utils/webpki/src/
{cert,verify_cert,signed_data}.rs) is host-side Python here — certificate
parsing is control-plane work; the report-signature modexps are the data
plane and run batched on TPU (ops/rsa.verify_batch → ops/bigmod).

Scope matches the reference's actual checks: end-entity certificate
chained directly to a pinned root (the IAS report-signing cert is issued
straight from Intel's attestation root; `intermediate_report` is empty at
lib.rs:150), validity window containing the pinned time, and the report
signature.  The root store is injectable: production pins Intel's root
DER; the node simulator pins a fixture CA and fabricates reports, the
same strategy as the reference's round-trip test
(enclave-verify/src/lib.rs:242-255).

Only RSA keys and sha256WithRSAEncryption signatures are supported — the
algorithms the IAS chain actually uses (webpki call at lib.rs:165-169
pins RSA_PKCS1_2048_8192_SHA256).
"""

from __future__ import annotations

import base64
import binascii
from dataclasses import dataclass

from ..ops import rsa

# Reference pins 2022-12-09 00:00:00 UTC (enclave-verify/src/lib.rs:151).
FIXED_VERIFY_TIME = 1670515200

# DER OIDs (encoded, without tag/length)
_OID_SHA256_RSA = bytes.fromhex("2a864886f70d01010b")  # 1.2.840.113549.1.1.11
_OID_RSA_ENC = bytes.fromhex("2a864886f70d010101")  # 1.2.840.113549.1.1.1
_OID_CN = bytes.fromhex("550403")  # 2.5.4.3


class DerError(ValueError):
    pass


# ---------------------------------------------------------------- DER read


def _read_tlv(data: bytes, off: int) -> tuple[int, bytes, int]:
    """One TLV: returns (tag, content, offset past the element)."""
    if off + 2 > len(data):
        raise DerError("truncated TLV header")
    tag = data[off]
    length = data[off + 1]
    off += 2
    if length & 0x80:
        nbytes = length & 0x7F
        if nbytes == 0 or nbytes > 4 or off + nbytes > len(data):
            raise DerError("bad long-form length")
        length = int.from_bytes(data[off : off + nbytes], "big")
        off += nbytes
    if off + length > len(data):
        raise DerError("content overruns buffer")
    return tag, data[off : off + length], off + length


def _expect(data: bytes, off: int, want_tag: int) -> tuple[bytes, int]:
    tag, content, nxt = _read_tlv(data, off)
    if tag != want_tag:
        raise DerError(f"expected tag {want_tag:#x}, got {tag:#x}")
    return content, nxt


def _der_int(content: bytes) -> int:
    if not content:
        raise DerError("empty INTEGER")
    return int.from_bytes(content, "big")


def _parse_time(tag: int, content: bytes) -> int:
    """UTCTime/GeneralizedTime → unix seconds (UTC, 'Z' suffix only).
    Every malformed-bytes failure maps to DerError so crafted
    certificates cannot crash the verifier."""
    try:
        s = content.decode("ascii")
    except UnicodeDecodeError as e:
        raise DerError("non-ASCII time") from e
    if not s.endswith("Z"):
        raise DerError("non-UTC time")
    s = s[:-1]
    try:
        if tag == 0x17:  # UTCTime YYMMDDHHMMSS
            year = int(s[0:2])
            year += 2000 if year < 50 else 1900
            rest = s[2:]
        elif tag == 0x18:  # GeneralizedTime YYYYMMDDHHMMSS
            year = int(s[0:4])
            rest = s[4:]
        else:
            raise DerError("unknown time tag")
        month, day = int(rest[0:2]), int(rest[2:4])
        hour, minute = int(rest[4:6]), int(rest[6:8])
        sec = int(rest[8:10]) if len(rest) >= 10 else 0
    except ValueError as e:
        raise DerError("malformed time digits") from e
    # days since epoch (proleptic Gregorian, no tz)
    y, m = year, month
    if m <= 2:
        y, m = y - 1, m + 12
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m - 3) + 2) // 5 + day - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    days = era * 146097 + doe - 719468
    return ((days * 24 + hour) * 60 + minute) * 60 + sec


@dataclass(frozen=True)
class Certificate:
    """The fields `verify_cert`-style validation needs (the webpki
    EndEntityCert role, reference: utils/webpki/src/cert.rs)."""

    tbs_raw: bytes  # the signed bytes (full TBSCertificate TLV)
    issuer: bytes  # raw Name DER (byte-compared, as webpki does)
    subject: bytes
    not_before: int
    not_after: int
    public_key: rsa.RsaPublicKey
    sig_alg_oid: bytes
    signature: bytes


def parse_certificate(der: bytes) -> Certificate:
    cert_body, end = _expect(der, 0, 0x30)
    if end != len(der):
        raise DerError("trailing bytes after certificate")
    # re-read inside the outer SEQUENCE
    base = der[: end]
    inner_off = end - len(cert_body)
    # tbsCertificate: keep the RAW TLV (it is what the CA signed)
    tbs_tag, tbs_content, tbs_end = _read_tlv(base, inner_off)
    if tbs_tag != 0x30:
        raise DerError("bad tbsCertificate")
    tbs_raw = base[inner_off:tbs_end]
    # signatureAlgorithm
    alg_content, alg_end = _expect(base, tbs_end, 0x30)
    alg_oid, _ = _expect(alg_content, 0, 0x06)
    # signatureValue
    sig_tag, sig_content, sig_end = _read_tlv(base, alg_end)
    if sig_tag != 0x03 or not sig_content or sig_content[0] != 0:
        raise DerError("bad signature BIT STRING")
    signature = sig_content[1:]
    if sig_end != end:
        raise DerError("trailing bytes in certificate body")

    # --- walk the TBS fields
    off = 0
    tag, _, nxt = _read_tlv(tbs_content, off)
    if tag == 0xA0:  # [0] EXPLICIT version
        off = nxt
        tag, _, nxt = _read_tlv(tbs_content, off)
    if tag != 0x02:
        raise DerError("missing serialNumber")
    off = nxt  # past serialNumber
    _, off = _expect(tbs_content, off, 0x30)  # signature AlgorithmIdentifier
    iss_tag, iss_content, iss_end = _read_tlv(tbs_content, off)
    if iss_tag != 0x30:
        raise DerError("bad issuer Name")
    issuer = tbs_content[off:iss_end]
    validity, off = _expect(tbs_content, iss_end, 0x30)
    t1_tag, t1, t1_end = _read_tlv(validity, 0)
    t2_tag, t2, _ = _read_tlv(validity, t1_end)
    not_before = _parse_time(t1_tag, t1)
    not_after = _parse_time(t2_tag, t2)
    subj_tag, subj_content, subj_end = _read_tlv(tbs_content, off)
    if subj_tag != 0x30:
        raise DerError("bad subject Name")
    subject = tbs_content[off:subj_end]
    spki, _ = _expect(tbs_content, subj_end, 0x30)
    spki_alg, spki_off = _expect(spki, 0, 0x30)
    key_oid, _ = _expect(spki_alg, 0, 0x06)
    if key_oid != _OID_RSA_ENC:
        raise DerError("unsupported key algorithm")
    bit_tag, bit_content, _ = _read_tlv(spki, spki_off)
    if bit_tag != 0x03 or not bit_content or bit_content[0] != 0:
        raise DerError("bad subjectPublicKey")
    rsakey, _ = _expect(bit_content[1:], 0, 0x30)
    n_content, n_end = _expect(rsakey, 0, 0x02)
    e_content, _ = _expect(rsakey, n_end, 0x02)
    return Certificate(
        tbs_raw=tbs_raw,
        issuer=issuer,
        subject=subject,
        not_before=not_before,
        not_after=not_after,
        public_key=rsa.RsaPublicKey(_der_int(n_content), _der_int(e_content)),
        sig_alg_oid=alg_oid,
        signature=signature,
    )


# ---------------------------------------------------------------- chain


@dataclass(frozen=True)
class RootStore:
    """Pinned trust anchors (the IAS_SERVER_ROOTS role, reference:
    enclave-verify/src/lib.rs:46-93): subject Name DER → RSA key."""

    roots: tuple[Certificate, ...]

    @classmethod
    def from_der(cls, ders: list[bytes]) -> "RootStore":
        return cls(tuple(parse_certificate(d) for d in ders))

    def key_for_issuer(self, issuer: bytes) -> rsa.RsaPublicKey | None:
        for root in self.roots:
            if root.subject == issuer:
                return root.public_key
        return None


def verify_cert(
    cert: Certificate, roots: RootStore, at_time: int = FIXED_VERIFY_TIME
) -> bool:
    """End-entity validation against the pinned roots at a fixed time —
    the webpki verify_is_valid_tls_server_cert role as the reference uses
    it (no intermediates, fixed clock; enclave-verify/src/lib.rs:148-158).
    """
    if cert.sig_alg_oid != _OID_SHA256_RSA:
        return False
    if not cert.not_before <= at_time <= cert.not_after:
        return False
    issuer_key = roots.key_for_issuer(cert.issuer)
    if issuer_key is None:
        return False
    return rsa.verify(issuer_key, cert.tbs_raw, cert.signature)


# ---------------------------------------------------------------- reports


def _b64(data: bytes) -> bytes | None:
    try:
        return base64.b64decode(data, validate=True)
    except (binascii.Error, ValueError):
        return None


def verify_attestation(
    sign: bytes,
    cert_der_b64: bytes,
    report_json_raw: bytes,
    roots: RootStore,
    at_time: int = FIXED_VERIFY_TIME,
) -> bool:
    """Single-report path, mirroring verify_miner_cert's order of checks
    (reference: enclave-verify/src/lib.rs:135-219): decode cert → chain
    check → decode signature → report-signature check."""
    out = verify_attestation_batch(
        [(sign, cert_der_b64, report_json_raw)], roots, at_time
    )
    return out[0]


def verify_attestation_batch(
    reports: list[tuple[bytes, bytes, bytes]],
    roots: RootStore,
    at_time: int = FIXED_VERIFY_TIME,
) -> list[bool]:
    """Batched attestation verification: the certificate chain checks are
    host-side; the report signatures are grouped per signing key and run
    through the batched device modexp (ops/rsa.verify_batch).  Verdicts
    are bit-identical to the single path."""
    parsed: list[tuple[int, rsa.RsaPublicKey, bytes, bytes] | None] = []
    for idx, (sign, cert_der_b64, report_json) in enumerate(reports):
        cert_der = _b64(cert_der_b64)
        sig = _b64(sign)
        if cert_der is None or sig is None:
            parsed.append(None)
            continue
        try:
            cert = parse_certificate(cert_der)
        except DerError:
            parsed.append(None)
            continue
        if not verify_cert(cert, roots, at_time):
            parsed.append(None)
            continue
        parsed.append((idx, cert.public_key, report_json, sig))

    verdicts = [False] * len(reports)
    by_key: dict[rsa.RsaPublicKey, list[tuple[int, bytes, bytes]]] = {}
    for entry in parsed:
        if entry is None:
            continue
        idx, key, msg, sig = entry
        by_key.setdefault(key, []).append((idx, msg, sig))
    for key, items in by_key.items():
        results = rsa.verify_batch(key, [(m, s) for _, m, s in items])
        for (idx, _, _), ok in zip(items, results):
            verdicts[idx] = ok
    return verdicts


# ---------------------------------------------------------------- fixtures
# Minimal DER writer for test/simulator certificates — the counterpart of
# the reference's round-trip fixtures (enclave-verify/src/lib.rs:242-255).


def _tlv(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    blen = (n.bit_length() + 7) // 8
    return bytes([tag, 0x80 | blen]) + n.to_bytes(blen, "big") + content


def _der_int_enc(x: int) -> bytes:
    raw = x.to_bytes((x.bit_length() + 7) // 8 or 1, "big")
    if raw[0] & 0x80:
        raw = b"\x00" + raw
    return _tlv(0x02, raw)


def _name(cn: str) -> bytes:
    atv = _tlv(
        0x30,
        _tlv(0x06, _OID_CN) + _tlv(0x0C, cn.encode()),
    )
    return _tlv(0x30, _tlv(0x31, atv))


def _utc(ts: int) -> bytes:
    days = ts // 86400
    rem = ts % 86400
    # inverse of the civil-from-days conversion above
    z = days + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + 3 if mp < 10 else mp - 9
    if m <= 2:
        y += 1
    s = f"{y % 100:02d}{m:02d}{d:02d}{rem // 3600:02d}{(rem % 3600) // 60:02d}{rem % 60:02d}Z"
    return _tlv(0x17, s.encode())


def build_certificate(
    subject_cn: str,
    issuer_cn: str,
    subject_key: rsa.RsaPublicKey,
    issuer_priv: rsa.RsaPrivateKey,
    not_before: int,
    not_after: int,
    serial: int = 1,
) -> bytes:
    """DER X.509 v3 certificate signed sha256WithRSAEncryption."""
    sig_alg = _tlv(0x30, _tlv(0x06, _OID_SHA256_RSA) + _tlv(0x05, b""))
    spki = _tlv(
        0x30,
        _tlv(0x30, _tlv(0x06, _OID_RSA_ENC) + _tlv(0x05, b""))
        + _tlv(
            0x03,
            b"\x00"
            + _tlv(
                0x30,
                _der_int_enc(subject_key.n) + _der_int_enc(subject_key.e),
            ),
        ),
    )
    tbs = _tlv(
        0x30,
        _tlv(0xA0, _der_int_enc(2))  # version v3
        + _der_int_enc(serial)
        + sig_alg
        + _name(issuer_cn)
        + _tlv(0x30, _utc(not_before) + _utc(not_after))
        + _name(subject_cn)
        + spki,
    )
    signature = rsa.sign(issuer_priv, tbs)
    return _tlv(0x30, tbs + sig_alg + _tlv(0x03, b"\x00" + signature))


def fixture_authority(rng=None, bits: int = 2048):
    """A self-signed fixture root + its key (simulator genesis)."""
    priv = rsa.keygen(bits, rng)
    der = build_certificate(
        "CESS Sim Attestation Root",
        "CESS Sim Attestation Root",
        priv.public(),
        priv,
        not_before=FIXED_VERIFY_TIME - 86400 * 365,
        not_after=FIXED_VERIFY_TIME + 86400 * 3650,
    )
    return der, priv


def fixture_report(
    issuer_priv: rsa.RsaPrivateKey,
    report_json: bytes,
    rng=None,
    bits: int = 2048,
    issuer_cn: str = "CESS Sim Attestation Root",
):
    """(sign, cert_der_b64, report_json) as a registering TEE submits."""
    signer = rsa.keygen(bits, rng)
    cert = build_certificate(
        "CESS Sim Report Signer",
        issuer_cn,
        signer.public(),
        issuer_priv,
        not_before=FIXED_VERIFY_TIME - 86400,
        not_after=FIXED_VERIFY_TIME + 86400 * 365,
        serial=7,
    )
    sig = rsa.sign(signer, report_json)
    return base64.b64encode(sig), base64.b64encode(cert), report_json


# ---------------------------------------------------------------- binding


def report_binds_key(report_json_raw: bytes, podr2_pbk: bytes) -> bool:
    """The attested report must bind the PoDR2 public key the worker is
    registering — otherwise any valid attestation triple could be
    replayed to register an arbitrary key.  (The reference extracts the
    worker key FROM the verified quote body rather than trusting the
    extrinsic's copy: enclave-verify/src/lib.rs:176-219.)  The report is
    JSON with a `podr2_pbk` hex field; parse failures bind nothing."""
    import json

    try:
        body = json.loads(report_json_raw)
    except (ValueError, UnicodeDecodeError):
        return False
    field = body.get("podr2_pbk")
    return isinstance(field, str) and field == podr2_pbk.hex()
