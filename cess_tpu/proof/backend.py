"""ProofBackend interface: the batch protocol between the chain layer and
the PoDR2 math.

Batch protocol (SURVEY.md §7 item 3): (challenge snapshot, proofs[], keys)
→ verdict bitmap.  Backends must be deterministic and mutually bit-identical
— the audit round's accept/reject decisions are consensus-critical.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass

from ..ops.podr2 import Challenge, Podr2Params, Podr2Proof

# One verification item: a fragment name, the round challenge, the proof.
VerifyItem = tuple  # (name: bytes, challenge: Challenge, proof: Podr2Proof)


@dataclass
class ProveRequest:
    """Miner-side batch: produce proofs for many fragments under one round
    challenge (all miners share the round's indices/coefficients, reference:
    c-pallets/audit/src/types.rs:14-23 — one NetSnapShot per round)."""

    names: list[bytes]
    tags: list[list[bytes]]      # per fragment: n chunk tags
    data: list[bytes]            # per fragment: raw bytes
    challenge: Challenge
    params: Podr2Params


class ProofBackend(ABC):
    """Pluggable PoDR2 executor."""

    name: str = "abstract"

    @abstractmethod
    def verify_batch(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
    ) -> list[bool]:
        """Per-item verdicts.  Implementations batch-combine with the shared
        ρ weights derived from `seed` and bisect on failure, so the common
        all-honest case costs O(1) pairings."""

    @abstractmethod
    def prove_batch(self, request: ProveRequest) -> list[Podr2Proof]:
        """Miner-side proof generation for a batch of fragments."""

    # -- shared bisection ------------------------------------------------

    def _verdicts_by_bisection(
        self,
        pk: bytes,
        items: list[VerifyItem],
        seed: bytes,
        params: Podr2Params,
        batch_check,
        single_check,
    ) -> list[bool]:
        """Deterministic divide-and-conquer: one combined check per node of
        the bisection tree; leaves fall back to single verification.  Both
        backends use this exact strategy so verdict computation (not just
        verdict values) matches."""
        verdicts = [False] * len(items)

        def recurse(indices: list[int], depth: int) -> None:
            subset = [items[i] for i in indices]
            if batch_check(pk, subset, seed + depth.to_bytes(2, "little"), params):
                for i in indices:
                    verdicts[i] = True
                return
            if len(indices) == 1:
                verdicts[indices[0]] = single_check(pk, subset[0], params)
                return
            mid = len(indices) // 2
            recurse(indices[:mid], depth + 1)
            recurse(indices[mid:], depth + 1)

        if items:
            recurse(list(range(len(items))), 0)
        return verdicts
