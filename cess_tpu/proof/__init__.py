"""ProofBackend seam (SURVEY.md §7 L2).

The reference leaves proof verification as a declared TODO at the chain
boundary (reference: c-pallets/audit/src/lib.rs:484 "TODO! Podr2Key verify")
and runs the real PoDR2 math in external TEE tooling.  This package is that
seam made explicit: a backend interface with

  * cpu  — pure-host reference (ops/podr2.py), the bit-exactness anchor;
  * xla  — the TPU path: μ aggregation / batch combination as MXU limb
           matmuls (ops/fr.py), G1/pairing work host-side pending the
           ops/g1.py device kernels.

Both produce identical verdict bitmaps for identical inputs.
"""

from .backend import ProofBackend, VerifyItem
from .cpu_backend import CpuBackend
from .xla_backend import XlaBackend


def get_backend(name: str = "cpu", **kwargs) -> ProofBackend:
    if name == "cpu":
        return CpuBackend(**kwargs)
    if name == "xla":
        return XlaBackend(**kwargs)
    raise ValueError(f"unknown proof backend {name!r}")


__all__ = [
    "ProofBackend",
    "VerifyItem",
    "CpuBackend",
    "XlaBackend",
    "get_backend",
]
