"""Fused PoDR2 batch-verify pipeline — the north-star fast path.

The round-3 bench put the marginal verify cost at 6.3 ms/proof; the
budget for "100k proofs + 10 GiB RS < 60 s" is ~0.5 ms/proof.  The gap
was structural, not arithmetic: every stage of the combined check ran
as its own device dispatch with host staging in between (device→host
pulls of intermediate points cost ~100-300 ms each on any link, and the
σ subgroup checks ran as per-point Python ladders).  This module runs
the whole per-chunk group computation as ONE jitted device program:

  u words ──unpack──► SSWU map (Pallas) ──► GLV grouped fold (Pallas:
  cofactor clear → φ table → 64-step 2-bit ladder) ──gather/mask──►
  per-proof tree reduce ──► ρ fold ─┐
  σ limbs ──► subgroup chain + ρ fold ──► partial lhs               │
  μ words ──unpack──► MXU combine (ops/fr.py) ──► partial exponents │
                                                                    ▼
                       chunk partials accumulate ON DEVICE; one final
                       device→host pull (two points + 265 exponents),
                       u-side fold, two pairings on host.

Transfers are packed to their information content (u: 96 B/pair,
μ: 32 B/sector, σ: projective limb words).  The host front-end is the
vectorised batch form (proof/frontend.py: batched σ decompression, one
shared encode pass for transcript + μ words, word-level ρ packing), and
chunks run a REAL double buffer: a one-worker prefetch pool packs chunk
k+1's inputs while chunk k's program executes under JAX async dispatch,
with nothing blocking on device values until every chunk is in flight
(the double-buffering called for by SURVEY.md §7 hard part 5; the
`dispatch_wait` stage histogram is the un-hidden device remainder —
docs/perf.md).  With _one_shape() active every chunk pads to CHUNK
proofs so `_verify_chunk_device` compiles exactly once per process,
counted by COMPILE_COUNTS.

Verdicts are bit-identical to the host reference (ops/podr2.py
batch_verify): same ρ transcript, same zip-truncation semantics, same
rejection set (bad σ encodings and non-subgroup σ reject the batch —
the subgroup test runs as a device [r]-chain instead of the host's
per-point Python ladder).  Asserted in tests/test_fused.py.

Capability match: the reference's pairing-side verify
(utils/verify-bls-signatures/src/lib.rs:85-100) at the audit seam
(c-pallets/audit/src/lib.rs:484).
"""

from __future__ import annotations

import os
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import bls12_381 as bls
from ..ops import fr, g1, glv, h2c, podr2
from ..ops.bls12_381 import G1Point, G2Point, R
from ..ops.podr2 import Podr2Params
from . import frontend

# Proofs per device program: bounds HBM footprint and compile count
# (every chunk of the same size reuses the executable).
CHUNK = 1024

# Trace-time counters for the jitted chunk programs: jax re-traces only
# on a new argument-shape signature, so the count is the number of
# distinct compiled executables this process built — the measurable form
# of the one-shape invariant (tests/test_proof_hotpath.py asserts a
# multi-chunk verify_batch compiles _verify_chunk_device exactly once).
COMPILE_COUNTS = {"verify_chunk": 0}


def _one_shape() -> bool:
    """Pad every fused sub-batch to CHUNK proofs (dead lanes σ=∞, ρ=0,
    μ=0) so `_verify_chunk_device` sees ONE shape per process.  Default:
    on for TPU (a fused-program compile costs minutes; dead lanes cost
    microseconds), off for the CPU test mesh (where tiny exact-shape
    programs compile fast and padded ones run slow).
    CESS_FUSED_ONE_SHAPE=1/0 forces either way."""
    env = os.environ.get("CESS_FUSED_ONE_SHAPE")
    if env is not None:
        return env not in ("0", "false", "off")
    return jax.default_backend() == "tpu"


# One-deep host-prep prefetch: while chunk k's device program runs
# (JAX async dispatch), the worker packs chunk k+1's inputs — XMD
# hashing (native, GIL-releasing), limb packing, lane maps.  A single
# worker is the whole double buffer: one chunk in prep, one in flight.
_PREP_POOL: ThreadPoolExecutor | None = None
_PREP_POOL_LOCK = threading.Lock()


def _prep_pool() -> ThreadPoolExecutor:
    global _PREP_POOL
    with _PREP_POOL_LOCK:
        if _PREP_POOL is None:
            _PREP_POOL = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="fused-prep"
            )
    return _PREP_POOL


# ------------------------------------------------------------ host packing


def pack_u_words(u_be: np.ndarray) -> np.ndarray:
    """(N, 2, 48) big-endian field bytes → (N, 2, 12) uint32 little-endian
    value words (the densest transfer form; device unpacks to limbs)."""
    le = u_be[..., ::-1].copy()  # little-endian byte order
    return le.view("<u4").reshape(u_be.shape[0], 2, 12)


def pack_mu_words(mus: list[list[int]]) -> np.ndarray:
    """B×S μ scalars (< 2^255) → (B, S, 8) uint32 little-endian words.

    The verify pipeline no longer calls this per proof: one shared
    proof.encode() pass feeds both the transcript and the μ words
    (proof/frontend.py mu_words — a numpy view over the encodings, so
    the int→byte conversion happens once).  Kept, vectorised, for
    callers that hold scalar matrices (bench crafting, tests)."""
    b = len(mus)
    s = len(mus[0]) if b else 0
    buf = b"".join(m.to_bytes(32, "little") for row in mus for m in row)
    return np.frombuffer(buf, dtype="<u4").reshape(b, s, 8)


def pack_points_limbs(points: list[G1Point]) -> tuple[np.ndarray, ...]:
    """Host points → (33, N) int32 limb triples via one vectorised byte
    pass (no per-limb Python loops — ~100× points_to_projective)."""
    n = len(points)
    raw = bytearray(n * 2 * 48)
    zs = np.zeros((n,), dtype=np.int32)
    for i, p in enumerate(points):
        if p.is_infinity():
            continue
        raw[i * 96 : i * 96 + 48] = p.x.to_bytes(48, "big")
        raw[i * 96 + 48 : i * 96 + 96] = p.y.to_bytes(48, "big")
        zs[i] = 1
    be = np.frombuffer(bytes(raw), dtype=np.uint8).reshape(n, 2, 48)
    limbs = h2c.u_bytes_to_limbs(be)  # (33, n, 2)
    X = limbs[:, :, 0]
    Y = np.where(zs[None, :] == 1, limbs[:, :, 1], 0)
    Y[0] = np.where(zs == 1, Y[0], 1)  # ∞ = (0 : 1 : 0)
    Z = np.zeros_like(X)
    Z[0] = zs
    return X, Y, Z


# ------------------------------------------------------------ device unpack


def _u_words_to_limbs(words: jnp.ndarray) -> jnp.ndarray:
    """(N, 2, 12) uint32 → (33, 2, N) int32 base-4096 limbs."""
    w = words.astype(jnp.uint32)
    rows = []
    for i in range(g1.L):
        lo_bit = 12 * i
        wi, sh = lo_bit // 32, lo_bit % 32
        if wi >= 12:
            rows.append(jnp.zeros(w.shape[:2], jnp.uint32))
            continue
        val = w[..., wi] >> sh
        if sh > 20 and wi + 1 < 12:
            val = val | (w[..., wi + 1] << (32 - sh))
        rows.append(val & 0xFFF)
    out = jnp.stack(rows).astype(jnp.int32)  # (33, N, 2)
    return jnp.swapaxes(out, 1, 2)


def _mu_words_to_limbs(words: jnp.ndarray) -> jnp.ndarray:
    """(B, S, 8) uint32 → (B, S, 37) int8 base-128 limbs (fr codec)."""
    w = words.astype(jnp.uint32)
    rows = []
    for i in range(fr.NLIMBS):
        lo_bit = 7 * i
        wi, sh = lo_bit // 32, lo_bit % 32
        val = w[..., wi] >> sh
        if sh > 25 and wi + 1 < 8:
            val = val | (w[..., wi + 1] << (32 - sh))
        rows.append(val & 0x7F)
    return jnp.stack(rows, axis=-1).astype(jnp.int8)


# ------------------------------------------------------------ device chunk


def _tree_reduce_last(points):
    """Σ over the last axis, padded to a power of two with identity
    points (0 : 1 : 0) first — g1.tree_reduce's pairwise halving
    silently drops lanes on odd axis lengths, so a 3- or 5-chunk batch
    (tests/test_zz_fused_multichunk.py) must never reach it unpadded."""
    X, Y, Z = points
    n = X.shape[-1]
    npow = 1 << max(0, (n - 1).bit_length())
    if npow != n:
        pad = [(0, 0)] * (X.ndim - 1) + [(0, npow - n)]
        X = jnp.pad(X, pad)
        Z = jnp.pad(Z, pad)
        Y = jnp.concatenate(
            [Y, glv._limb_one(Y[..., : npow - n]).astype(Y.dtype)],
            axis=-1,
        )
    return g1.tree_reduce((X, Y, Z), npow)


@jax.jit
def _verify_chunk_device(
    u_words, flags, v_k1, v_k2, lane_map, lane_mask,
    sX, sY, sZ, rho_digits, rho_i8, mu_words,
):
    """One chunk's whole group computation on device.

    u_words (Np, 2, 12) uint32; flags (Np,) int32 (native XMD predicate
    bits); v_k1/v_k2 (11, Np) GLV digit halves of v_c (coefficient of
    each pair's lane); lane_map/lane_mask (B, G) int32 gather map from
    lanes to per-proof groups; sX/sY/sZ (33, B) σ limbs; rho_digits
    (22, B) ladder limbs; rho_i8 (B, 19) int8 fr limbs; mu_words
    (B, S, 8) uint32.  Returns partial lhs/rhs triples (33,), exps
    (S, 37) and the σ subgroup mask (B,)."""
    COMPILE_COUNTS["verify_chunk"] += 1  # trace-time: one per shape
    B, G = lane_map.shape

    # hash-to-curve: unpack u, split predicates, run the fused map
    u_limbs = _u_words_to_limbs(u_words)
    f = flags.astype(jnp.int32)
    sgn = jnp.stack([f & 1, (f >> 2) & 1])
    exc = jnp.stack([(f >> 1) & 1, (f >> 3) & 1])
    hX, hY, hZ = h2c._map_pairs_kernel(u_limbs, sgn, exc)

    # GLV grouped fold: clear cofactor, then [v_c] per lane
    aX, aY, aZ = glv.glv_fold(hX, hY, hZ, v_k1, v_k2, clear=True)

    # gather into per-proof groups (dead slots masked to ∞), tree-reduce
    flat = lane_map.reshape(-1)
    m = lane_mask.reshape(-1)[None]
    gX = jnp.where(m == 1, jnp.take(aX, flat, axis=1), 0)
    gY = jnp.take(aY, flat, axis=1)
    gY = jnp.where(m == 1, gY, glv._limb_one(gY))
    gZ = jnp.where(m == 1, jnp.take(aZ, flat, axis=1), 0)
    inner = g1.tree_reduce(
        tuple(a.reshape(g1.L, B, G) for a in (gX, gY, gZ)), G
    )

    # ρ folds: H-side over the inner points, σ-side over the proofs
    racc = g1.batch_scalar_mul(inner, rho_digits, bits=128)
    rhsX, rhsY, rhsZ = _tree_reduce_last(
        tuple(a[:, None, :] for a in racc)
    )
    sacc = g1.batch_scalar_mul((sX, sY, sZ), rho_digits, bits=128)
    lhsX, lhsY, lhsZ = _tree_reduce_last(
        tuple(a[:, None, :] for a in sacc)
    )
    mask = glv.subgroup_mask(sX, sY, sZ)

    # u-side exponents: Σ_b ρ_b μ_bj on the MXU
    mu_limbs = _mu_words_to_limbs(mu_words)
    exps = fr.weighted_sum_kernel(
        rho_i8, jnp.moveaxis(mu_limbs, 0, -2)
    )  # (S, 37)

    return (
        (lhsX[..., 0], lhsY[..., 0], lhsZ[..., 0]),
        (rhsX[..., 0], rhsY[..., 0], rhsZ[..., 0]),
        exps,
        mask,
    )


@jax.jit
def _accumulate_points(stackX, stackY, stackZ):
    """(33, K) chunk partials → one projective total."""
    return _tree_reduce_last(
        tuple(a[:, None, :] for a in (stackX, stackY, stackZ))
    )


@jax.jit
def _finalize_exps(parts):
    """(K, S, 37) canonical chunk partials → (S, 37) canonical total."""
    total = jnp.sum(parts.astype(jnp.int32), axis=0)
    total = fr._normalize(
        jnp.pad(total, [(0, 0)] * (total.ndim - 1) + [(0, 3)])
    )
    return fr._fold_to_canonical(total)


# ------------------------------------------------------------ GLV cache


@lru_cache(maxsize=1 << 14)
def _v_digits(v: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-coefficient GLV digit rows (cached — a live round shares its
    47 coefficients across every proof of the round's challenge)."""
    k1, k2 = glv.decompose_to_limbs([v])
    return k1[:, 0], k2[:, 0]


# ------------------------------------------------------------ pipeline


@dataclass
class _ChunkOut:
    lhs: tuple
    rhs: tuple
    exps: object
    mask: object


def _tile_pad(n: int, tile: int) -> int:
    return -(-n // tile) * tile


def combined_check_fused(
    pk: bytes,
    items: list,
    seed: bytes,
    params: Podr2Params,
    stages: dict | None = None,
) -> bool:
    """Bit-identical replacement for the stage-by-stage combined check.

    Semantics (must match ops/podr2.py batch_verify exactly):
      * empty batch → True
      * undecodable pk or σ, wrong μ width, out-of-range μ, or a σ
        outside the r-order subgroup → False
      * otherwise the single combined pairing equation decides.

    Host front-end is the vectorised batch form (proof/frontend.py):
    batched σ decompression with the subgroup test left on the device
    chain, ONE proof.encode() pass feeding transcript + μ words, and
    word-level ρ packing.  Chunks run through a double-buffered
    pipeline: chunk k's device program is dispatched asynchronously
    while a prefetch worker packs chunk k+1's host inputs, and nothing
    blocks on device values until every chunk is in flight — the
    `dispatch_wait` stage below is exactly the device time the host
    prep failed to hide.

    Telemetry mirrors the staged path (same histogram names +
    dispatch_wait; cess_proof_* counters), and `stages` accumulates the
    per-call breakdown when the backend profiles."""
    if not items:
        return True
    from .xla_backend import (
        STAGE_METRICS_ENABLED,
        _observe_stage,
        _stage_counters,
        proof_stage_registry,
    )

    metered = STAGE_METRICS_ENABLED

    def mark(name, t0):
        if not metered and stages is None:
            return t0
        now = _time.perf_counter()
        if stages is not None:
            stages[name] = stages.get(name, 0.0) + (now - t0)
        if metered:
            _observe_stage(name, now - t0)
        return now

    check_t0 = _time.perf_counter()
    t0 = check_t0
    try:
        pk_point = G2Point.from_bytes(pk)
    except ValueError:
        return False
    sigmas = frontend.decompress_sigmas(items)
    if sigmas is None:
        return False
    if any(len(p.mu) != params.s for _, _, p in items):
        return False
    encs = frontend.encode_proofs(items)
    if encs is None:
        return False
    mu_w = frontend.mu_words(encs, params.s)
    if not frontend.mu_in_range(mu_w):
        return False
    batch_items = [podr2.BatchItem(n, c, p) for n, c, p in items]
    rhos = podr2.batch_rho(
        podr2.batch_transcript(seed, batch_items, encodings=encs),
        len(items),
    )

    # one program shape per call: every chunk shares (Bp, npad, g) —
    # and with _one_shape() they are process-constant for a given
    # challenge geometry, so _verify_chunk_device compiles once ever.
    chunk = CHUNK
    counts_all = [
        min(len(ch.indices), len(ch.randoms)) for _, ch, _ in items
    ]
    cnt_max = max(counts_all)
    g = 1 << max(0, (cnt_max - 1).bit_length())
    tile = max(h2c._MAP_TILE, glv._GLV_TILE)
    if _one_shape():
        pad_b = chunk
        pad_lanes = _tile_pad(max(chunk * cnt_max, 1), tile)
    else:
        pad_b = pad_lanes = None  # per-chunk pow2 / exact tiling

    spans = list(range(0, len(items), chunk))

    def prep(start):
        return _prep_chunk(
            items[start : start + chunk],
            sigmas[start : start + chunk],
            rhos[start : start + chunk],
            mu_w[start : start + chunk],
            counts_all[start : start + chunk],
            params, pad_b, pad_lanes, g, tile,
        )

    outs: list[_ChunkOut] = []
    pool = _prep_pool()
    fut = pool.submit(prep, spans[0])
    for si in range(len(spans)):
        host_in = fut.result()
        t0 = mark("host_prep", t0)
        if si + 1 < len(spans):
            fut = pool.submit(prep, spans[si + 1])
        outs.append(_launch_chunk(host_in))  # async device dispatch
        t0 = mark("chunk_program", t0)

    # one device reduction over the chunk partials, one host pull
    lhs = _accumulate_points(
        jnp.stack([o.lhs[0] for o in outs], axis=-1),
        jnp.stack([o.lhs[1] for o in outs], axis=-1),
        jnp.stack([o.lhs[2] for o in outs], axis=-1),
    )
    rhs = _accumulate_points(
        jnp.stack([o.rhs[0] for o in outs], axis=-1),
        jnp.stack([o.rhs[1] for o in outs], axis=-1),
        jnp.stack([o.rhs[2] for o in outs], axis=-1),
    )
    exps = _finalize_exps(jnp.stack([o.exps for o in outs]))
    masks = jnp.concatenate([o.mask for o in outs])
    t0 = mark("chunk_program", t0)
    jax.block_until_ready((lhs, rhs, exps, masks))
    t0 = mark("dispatch_wait", t0)

    if not bool(np.all(np.asarray(masks) == 1)):
        verdict = False
    else:
        lhs_pt = g1.projective_to_points(
            *(np.asarray(a).reshape(1, -1) for a in lhs)
        )[0]
        rhs_pt = g1.projective_to_points(
            *(np.asarray(a).reshape(1, -1) for a in rhs)
        )[0]
        exps_ints = fr.limbs_to_ints(np.asarray(exps))

        us = list(podr2.u_generators(params.s))
        rhs_pt = rhs_pt + _u_fold(us, exps_ints)
        t0 = mark("u_fold", t0)
        verdict = bls.pairing_check(
            [(lhs_pt, -bls.G2_GENERATOR), (rhs_pt, pk_point)]
        )
        mark("pairing", t0)
    if metered:
        proof_stage_registry()
        _stage_counters["checks"].inc()
        _stage_counters["proofs"].inc(len(items))
        _stage_counters["seconds"].inc(_time.perf_counter() - check_t0)
    return verdict


def _u_fold(us: list[G1Point], exps: list[int]) -> G1Point:
    """Π u_j^{e_j} over the fixed sector generators — once per combined
    check, via the GLV fold (subgroup inputs, no clear)."""
    n = len(us)
    npad = _tile_pad(n, glv._GLV_TILE)
    X, Y, Z = pack_points_limbs(us + [G1Point.infinity()] * (npad - n))
    k1 = np.zeros((glv.K_LIMBS, npad), dtype=np.int32)
    k2 = np.zeros((glv.K_LIMBS, npad), dtype=np.int32)
    for j, e in enumerate(exps):
        k1[:, j], k2[:, j] = _v_digits(int(e) % R)
    aX, aY, aZ = glv.glv_fold(
        jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z),
        jnp.asarray(k1), jnp.asarray(k2), clear=False,
    )
    tX, tY, tZ = _accumulate_points(aX, aY, aZ)
    return g1.projective_to_points(
        *(np.asarray(a).reshape(1, -1) for a in (tX, tY, tZ))
    )[0]


def _prep_chunk(
    sub, sigmas, rhos, mu_w, counts, params,
    pad_b: int | None, pad_lanes: int | None, g: int, tile: int,
):
    """Pack one chunk's device inputs on the host (runs on the prefetch
    worker while the previous chunk's program executes).  pad_b /
    pad_lanes pin the proof- and lane-axis padding (the one-shape
    invariant); None falls back to per-chunk pow2 / exact tiling."""
    B = len(sub)
    Bp = pad_b if pad_b is not None else 1 << max(0, (B - 1).bit_length())
    n_pairs = sum(counts)
    npad = (
        pad_lanes
        if pad_lanes is not None
        else _tile_pad(max(n_pairs, 1), tile)
    )

    # host XMD (native, threaded) → packed u words + predicate flags
    name_ids = np.repeat(np.arange(B, dtype=np.uint32), counts)
    indices = np.concatenate(
        [
            np.asarray(ch.indices[:c], dtype=np.uint64)
            for (_, ch, _), c in zip(sub, counts)
        ]
    ) if n_pairs else np.zeros((0,), dtype=np.uint64)
    names = [name for name, _, _ in sub]
    u, flags = _xmd_u(names, name_ids, indices)
    u_words = np.zeros((npad, 2, 12), dtype=np.uint32)
    u_words[:n_pairs] = pack_u_words(u)
    fl = np.zeros((npad,), dtype=np.int32)
    fl[:n_pairs] = flags

    # per-lane GLV halves of the challenge coefficients
    v_k1, v_k2, lane_map, lane_mask = _lane_scalars(
        sub, counts, npad, Bp, g
    )

    # pad the proof axis to Bp with (σ = ∞, ρ = 0, μ = 0) lanes: every
    # fold treats them as identity and [r]∞ = ∞ passes the mask
    sX, sY, sZ = pack_points_limbs(
        sigmas + [G1Point.infinity()] * (Bp - B)
    )
    rho_digits = np.zeros((g1.R_LIMBS, Bp), dtype=np.int32)
    rho_digits[:, :B] = frontend.rho_digits(rhos)
    rho_i8 = np.zeros((Bp, 19), dtype=np.int8)
    rho_i8[:B] = frontend.rho_limbs7(rhos)
    mu_words = np.zeros((Bp, params.s, 8), dtype=np.uint32)
    mu_words[:B] = mu_w

    return (
        u_words, fl, v_k1, v_k2, lane_map, lane_mask,
        sX, sY, sZ, rho_digits, rho_i8, mu_words,
    )


def _launch_chunk(host_in) -> _ChunkOut:
    """Upload one prepped chunk and dispatch its device program — JAX
    async dispatch returns immediately, so the caller's next prep (and
    the prefetch worker's) overlap this chunk's device compute."""
    lhs, rhs, exps, mask = _verify_chunk_device(
        *(jnp.asarray(a) for a in host_in)
    )
    return _ChunkOut(lhs, rhs, exps, mask)


def _lane_scalars(sub, counts, npad: int, Bp: int, g: int):
    """Per-lane GLV digit arrays + the lane→group gather map.  The
    all-same-challenge batch (one audit round's snapshot) takes a tiled
    fast path; mixed challenges fall back to the per-lane loop.  `g` is
    the group gather width, shared across chunks by the caller so every
    chunk program has one shape."""
    B = len(sub)
    v_k1 = np.zeros((glv.K_LIMBS, npad), dtype=np.int32)
    v_k2 = np.zeros((glv.K_LIMBS, npad), dtype=np.int32)
    lane_map = np.zeros((Bp, g), dtype=np.int32)
    lane_mask = np.zeros((Bp, g), dtype=np.int32)
    first_ch = sub[0][1] if sub else None
    uniform = B > 1 and all(it[1] is first_ch for it in sub)
    if uniform:
        cnt = counts[0]
        block1 = np.stack(
            [_v_digits(v)[0] for v in first_ch.coefficients()[:cnt]], axis=1
        )
        block2 = np.stack(
            [_v_digits(v)[1] for v in first_ch.coefficients()[:cnt]], axis=1
        )
        n_pairs = cnt * B
        v_k1[:, :n_pairs] = np.tile(block1, B)
        v_k2[:, :n_pairs] = np.tile(block2, B)
        lane_map[:B, :cnt] = (
            np.arange(B, dtype=np.int32)[:, None] * cnt
            + np.arange(cnt, dtype=np.int32)[None]
        )
        lane_mask[:B, :cnt] = 1
        return v_k1, v_k2, lane_map, lane_mask
    pos = 0
    for b, ((_, ch, _), cnt) in enumerate(zip(sub, counts)):
        coeffs = ch.coefficients()[:cnt]
        for k, v in enumerate(coeffs):
            v_k1[:, pos + k], v_k2[:, pos + k] = _v_digits(v)
            lane_map[b, k] = pos + k
            lane_mask[b, k] = 1
        pos += cnt
    return v_k1, v_k2, lane_map, lane_mask


@jax.jit
def _craft_device(u_words, flags, k1, k2, lane_map, lane_mask):
    """Benchmark/prover helper: per-group Π H^{s_c} over freshly hashed
    chunk points — the device form of σ-tag aggregation."""
    u_limbs = _u_words_to_limbs(u_words)
    f = flags.astype(jnp.int32)
    sgn = jnp.stack([f & 1, (f >> 2) & 1])
    exc = jnp.stack([(f >> 1) & 1, (f >> 3) & 1])
    hX, hY, hZ = h2c._map_pairs_kernel(u_limbs, sgn, exc)
    aX, aY, aZ = glv.glv_fold(hX, hY, hZ, k1, k2, clear=True)
    B, G = lane_map.shape
    flat = lane_map.reshape(-1)
    m = lane_mask.reshape(-1)[None]
    gX = jnp.where(m == 1, jnp.take(aX, flat, axis=1), 0)
    gY = jnp.take(aY, flat, axis=1)
    gY = jnp.where(m == 1, gY, glv._limb_one(gY))
    gZ = jnp.where(m == 1, jnp.take(aZ, flat, axis=1), 0)
    return g1.tree_reduce(
        tuple(a.reshape(g1.L, B, G) for a in (gX, gY, gZ)), G
    )


def craft_sigmas(
    names: list[bytes], challenge, scalars: list[int]
) -> list[G1Point]:
    """Π_c H(name‖i_c)^{s_c} for every name under one challenge, with the
    full pipeline on device (bench proof crafting: s_c = sk·v_c mod r
    yields valid zero-data proofs).  Measured on the bench rig the
    device route crafts ≈2× faster than the host path once compiled —
    the real win is freeing the host CPU during proofgen, not raw
    rate (BENCH_r04)."""
    B = len(names)
    Bp = 1 << max(0, (B - 1).bit_length())
    cnt = min(len(challenge.indices), len(challenge.randoms))
    n_pairs = B * cnt
    tile = max(h2c._MAP_TILE, glv._GLV_TILE)
    npad = _tile_pad(max(n_pairs, 1), tile)

    name_ids = np.repeat(np.arange(B, dtype=np.uint32), cnt)
    indices = np.tile(
        np.asarray(challenge.indices[:cnt], dtype=np.uint64), B
    )
    u, flags = _xmd_u(names, name_ids, indices)
    u_words = np.zeros((npad, 2, 12), dtype=np.uint32)
    u_words[:n_pairs] = pack_u_words(u)
    fl = np.zeros((npad,), dtype=np.int32)
    fl[:n_pairs] = flags

    k1 = np.zeros((glv.K_LIMBS, npad), dtype=np.int32)
    k2 = np.zeros((glv.K_LIMBS, npad), dtype=np.int32)
    b1 = np.stack([_v_digits(s % R)[0] for s in scalars[:cnt]], axis=1)
    b2 = np.stack([_v_digits(s % R)[1] for s in scalars[:cnt]], axis=1)
    k1[:, :n_pairs] = np.tile(b1, B)
    k2[:, :n_pairs] = np.tile(b2, B)

    g = 1 << max(0, (cnt - 1).bit_length())
    lane_map = np.zeros((Bp, g), dtype=np.int32)
    lane_mask = np.zeros((Bp, g), dtype=np.int32)
    lane_map[:B, :cnt] = (
        np.arange(B, dtype=np.int32)[:, None] * cnt
        + np.arange(cnt, dtype=np.int32)[None]
    )
    lane_mask[:B, :cnt] = 1

    sX, sY, sZ = _craft_device(
        jnp.asarray(u_words), jnp.asarray(fl),
        jnp.asarray(k1), jnp.asarray(k2),
        jnp.asarray(lane_map), jnp.asarray(lane_mask),
    )
    return g1.projective_to_points(
        np.asarray(sX).T[:B], np.asarray(sY).T[:B], np.asarray(sZ).T[:B]
    )


def _xmd_u(names, name_ids, indices):
    """Host expand_message_xmd batch (native when built, else pure)."""
    if len(name_ids) == 0:
        return (
            np.zeros((0, 2, 48), dtype=np.uint8),
            np.zeros((0,), dtype=np.uint8),
        )
    name_ids = np.ascontiguousarray(name_ids, dtype=np.uint32)
    indices = np.ascontiguousarray(indices, dtype=np.uint64)
    try:
        from .. import native

        return native.xmd_u_indexed(
            names, name_ids, indices, podr2.H_DST, threads=8
        )
    except (AssertionError, AttributeError, OSError, RuntimeError):
        return h2c._u_host_fallback(names, name_ids, indices, podr2.H_DST)
