"""Vectorized host front-end for the PoDR2 verify path.

The combined check's device kernels (proof/fused.py, ops/g1.py,
ops/fr.py) were fed by per-proof host Python: a scalar G1 decompression
per σ, 265 int.to_bytes per proof for μ packing, a per-limb Python loop
per μ for the staged path's fr limbs, and per-proof transcript hashing.
At B=1024 that front-end — not the group math — dominated the marginal
ms/proof (ROADMAP item 1, BENCH_r04/r05).  This module is the shared
batch form used by both the fused single-program pipeline and the
staged XlaBackend path:

  * ONE proof.encode() pass per batch feeds the Fiat–Shamir transcript
    (ops/podr2.py batch_transcript(encodings=...)) AND the μ word/limb
    packing (numpy views over the concatenated encodings — the int→byte
    conversion happens exactly once per proof).
  * μ range validation (0 ≤ μ < r) is a vectorised lexicographic word
    compare; negative / ≥ 2^256 values surface as encode OverflowError.
    The reject set is exactly the scalar reference's.
  * ρ weights pack to 12-bit MSM digits and 7-bit fr limbs through the
    word-level codecs in ops/fr.py instead of per-limb loops.

Everything here is bit-identical to the scalar forms it replaces —
asserted in tests/test_proof_hotpath.py (the `proof_hotpath` CI gate).
"""

from __future__ import annotations

import numpy as np

from ..ops import bls12_381 as bls
from ..ops import fr, g1
from ..ops.bls12_381 import R

MU_BYTES = 32

# little-endian uint32 words of r, for the vectorised range compare
_R_WORDS = np.frombuffer(R.to_bytes(MU_BYTES, "little"), dtype="<u4").copy()


def decompress_sigmas(items) -> list | None:
    """All σ blobs → points with the subgroup test DEFERRED (the caller
    runs one batched device [r]-chain — ops/glv.py subgroup_mask).
    Returns None when any blob is malformed: the scalar path raises
    ValueError there, which every combined check maps to the whole-batch
    False verdict (bisection then isolates the bad items)."""
    try:
        return bls.g1_decompress_batch(
            [p.sigma for _, _, p in items], check_subgroup=False
        )
    except ValueError:
        return None


def encode_proofs(items) -> list[bytes] | None:
    """One shared μ/σ encode pass (proof.encode() per item).  Returns
    None when any μ is negative or ≥ 2^256 — int.to_bytes raises
    OverflowError exactly there, and those values are a subset of what
    the scalar reference's 0 ≤ μ < r check rejects; the remaining
    out-of-range band [r, 2^256) is caught by mu_in_range on the packed
    words."""
    try:
        return [p.encode() for _, _, p in items]
    except OverflowError:
        return None


def mu_words(encodings: list[bytes], s: int) -> np.ndarray:
    """Concatenated proof encodings → (B, s, 8) uint32 little-endian μ
    words — a reinterpreting view, no per-scalar conversion."""
    buf = b"".join(e[48:] for e in encodings)
    return np.frombuffer(buf, dtype="<u4").reshape(len(encodings), s, 8)


def mu_in_range(words: np.ndarray) -> bool:
    """Vectorised 0 ≤ μ < r over packed words (strict lexicographic
    compare against r's words, most-significant first) — the word form
    of the scalar reference's per-μ range check."""
    lt = np.zeros(words.shape[:-1], dtype=bool)
    eq = np.ones(words.shape[:-1], dtype=bool)
    for k in range(words.shape[-1] - 1, -1, -1):
        wk = words[..., k]
        lt |= eq & (wk < _R_WORDS[k])
        eq &= wk == _R_WORDS[k]
    return bool(lt.all())


def mu_limbs(words: np.ndarray) -> np.ndarray:
    """(B, S, 8) μ words → (B, S, 37) int8 base-128 limbs (the fr codec
    shape the staged path and the mesh data plane consume)."""
    return fr.words_to_limbs(words, fr.LIMB_BITS, fr.NLIMBS, np.int8)


def rho_words(rhos: list[int]) -> np.ndarray:
    """128-bit ρ weights → (B, 4) uint32 words."""
    return fr.ints_to_words(rhos, 16)


def rho_digits(rhos: list[int]) -> np.ndarray:
    """ρ → (22, B) int32 base-4096 ladder digits (ops/g1.py scalar
    shape, limb-major)."""
    return fr.words_to_limbs(
        rho_words(rhos), g1.LIMB_BITS, g1.R_LIMBS, np.int32
    ).T


def rho_limbs7(rhos: list[int], width: int = 19) -> np.ndarray:
    """ρ → (B, width) int8 base-128 limbs (ops/fr.py weight shape)."""
    return fr.words_to_limbs(rho_words(rhos), fr.LIMB_BITS, width, np.int8)
