"""Micro-benchmark of the verify host front-end at a given batch size.

Measures the per-proof HOST Python the tentpole targets — σ
decompression, the Fiat–Shamir transcript + ρ derivation, and μ
packing/limb staging — so the number isolates the host residue on any
host.  On a pre-vectorization checkout the same phases run through the
scalar forms (per-σ G1Point.from_bytes including its host subgroup
ladder, per-proof transcript hashing, per-limb μ staging), so running
the tool from two checkouts on the same host gives an honest
before/after (BENCH_r06.json frontend_microbench).

On the vectorized checkout the subgroup test is no longer host work —
it rides the batched device [r]-chain — so it is timed (warm) and
reported separately as deferred_subgroup_device_s, outside the host
total: on a TPU that chain is batch-parallel device time; on a CPU
host it is emulation and honestly slow, but it is not the host-residue
metric this tool tracks.

Prints one JSON line.  BENCH_FRONTEND_PROOFS sets N (default 1024).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time


def main() -> None:
    from cess_tpu.ops import fr, podr2
    from cess_tpu.ops import bls12_381 as bls
    from cess_tpu.ops.podr2 import Challenge, Podr2Params

    try:
        from cess_tpu.proof import frontend
        from cess_tpu.proof.xla_backend import _subgroup_ok
    except ImportError:  # pre-vectorization checkout
        frontend = None
        _subgroup_ok = None

    B = int(os.environ.get("BENCH_FRONTEND_PROOFS", "1024"))
    params = Podr2Params()  # protocol geometry: s=265
    rnd = random.Random(0xF0E)
    indices = tuple(sorted(rnd.sample(range(params.n), 47)))
    challenge = Challenge(
        indices=indices, randoms=tuple(rnd.randbytes(20) for _ in indices)
    )
    # distinct valid σ points (subgroup members) + realistic μ vectors
    sigma_pool = [
        bls.G1_GENERATOR.mul(1000 + 7 * i).to_bytes()
        for i in range(min(B, 64))
    ]
    items = []
    for i in range(B):
        mu = [rnd.getrandbits(248) for _ in range(params.s)]
        proof = podr2.Podr2Proof(sigma_pool[i % len(sigma_pool)], mu)
        items.append((b"fe-frag-%06d" % i, challenge, proof))

    out = {"b": B, "vectorized": frontend is not None}

    # 1. σ decompression (before: from_bytes incl. its host subgroup
    # ladder — that ladder was host Python, i.e. exactly the residue)
    t0 = time.perf_counter()
    if frontend is not None:
        pts = frontend.decompress_sigmas(items)
        assert pts is not None
    else:
        pts = [bls.G1Point.from_bytes(p.sigma) for _, _, p in items]
    t_dec = time.perf_counter() - t0

    # 2. transcript + ρ (and the encode pass that feeds it)
    batch_items = [podr2.BatchItem(n, c, p) for n, c, p in items]
    t0 = time.perf_counter()
    if frontend is not None:
        encs = frontend.encode_proofs(items)
        tr = podr2.batch_transcript(b"fe-seed", batch_items, encodings=encs)
    else:
        encs = None
        tr = podr2.batch_transcript(b"fe-seed", batch_items)
    rhos = podr2.batch_rho(tr, B)
    t_tr = time.perf_counter() - t0

    # 3. μ range check + packing to device-ready limb staging
    t0 = time.perf_counter()
    if frontend is not None:
        words = frontend.mu_words(encs, params.s)
        assert frontend.mu_in_range(words)
        mu_limbs = frontend.mu_limbs(words)
    else:
        import numpy as np

        assert not any(
            not 0 <= m < bls.R for _, _, p in items for m in p.mu
        )
        mu_limbs = np.stack([fr.fr_to_limbs(p.mu) for _, _, p in items])
    t_mu = time.perf_counter() - t0

    total = t_dec + t_tr + t_mu
    out.update(
        decompress_s=round(t_dec, 3),
        transcript_rho_s=round(t_tr, 3),
        mu_pack_s=round(t_mu, 3),
        host_total_s=round(total, 3),
        host_per_proof_ms=round(total / B * 1000, 3),
    )

    if _subgroup_ok is not None:
        import jax

        _subgroup_ok(pts[:8])  # warm the mask program at the floor shape
        _subgroup_ok(pts)      # warm at the batch shape (compile excluded)
        t0 = time.perf_counter()
        assert _subgroup_ok(pts)
        out["deferred_subgroup_s"] = round(time.perf_counter() - t0, 3)
        env = os.environ.get("CESS_DEVICE_SUBGROUP")
        device = (
            env not in ("0", "false", "off")
            if env is not None
            else jax.default_backend() == "tpu"
        )
        out["subgroup_route"] = "device-chain" if device else "host-ladder"

    print(json.dumps(out))
    del mu_limbs, rhos


if __name__ == "__main__":
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    main()
