"""Read-plane load generator: a fleet of verifying light clients.

Drives `reads/s` against one or more read replicas the way the target
deployment would: every worker is a REAL `light.LightClient` — it
anchors on a verified justification first, then issues proof-batch
reads that it verifies against its own justified root.  Nothing is
trusted, so the measured rate is the rate of *verified* reads, not of
blind RPC round trips.

Workers are spread round-robin across the given endpoints, which is
exactly the horizontal-scaling claim under test (bench.py
BENCH_ONLY=light: two replicas should beat one).

    python tools/read_loadgen.py --replicas 127.0.0.1:19944,... \
        --chain local --clients 8 --reads 200

Also used as a library by the bench and the light-testnet e2e
(tests/test_zz_light_testnet.py) via `run_load`.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

sys.path.insert(0, ".")  # repo-root invocation

from cess_tpu.light import LightClient, LightClientError  # noqa: E402
from cess_tpu.node.chain_spec import load_spec  # noqa: E402
from cess_tpu.node.rpc import RpcError  # noqa: E402

# one proof-batch worth of reads per round trip: the whole-leaf
# surfaces every chain serves, present or provably absent
DEFAULT_READS = [
    ["staking", "validators", None],
    ["session", "keys", None],
    ["staking", "active_era", None],
    ["state", "balances.accounts", "alice"],
]


def run_load(
    endpoints: list[tuple[str, int]],
    spec,
    clients: int = 4,
    reads: int = 100,
    batch: list | None = None,
    timeout: float = 10.0,
) -> dict:
    """Run `clients` verifying light clients, `reads` proof-batch round
    trips each, spread round-robin over `endpoints`.  Returns
    {"reads", "verified_leaves", "errors", "seconds", "rps"} — rps
    counts only round trips whose every proof verified."""
    batch = batch if batch is not None else DEFAULT_READS
    norm = [(p, a, k) for p, a, k in batch]
    done = [0] * clients
    leaves = [0] * clients
    errors = [0] * clients

    def worker(idx: int) -> None:
        host, port = endpoints[idx % len(endpoints)]
        try:
            lc = LightClient.from_spec(spec, host, port, timeout=timeout)
            lc.sync()
        except (LightClientError, RpcError, OSError):
            errors[idx] = reads
            return
        for _ in range(reads):
            try:
                got = lc.read_batch(norm)
                done[idx] += 1
                leaves[idx] += len(got)
            except (LightClientError, RpcError, OSError):
                errors[idx] += 1

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = max(1e-9, time.perf_counter() - t0)
    total = sum(done)
    return {
        "endpoints": [f"{h}:{p}" for h, p in endpoints],
        "clients": clients,
        "reads": total,
        "verified_leaves": sum(leaves),
        "errors": sum(errors),
        "seconds": round(elapsed, 4),
        "rps": round(total / elapsed, 2),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", required=True,
                    help="comma-separated host:port replica endpoints")
    ap.add_argument("--chain", default="dev",
                    help="chain spec for the clients' trust anchors")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--reads", type=int, default=100,
                    help="proof-batch round trips per client")
    args = ap.parse_args(argv)

    endpoints = []
    for part in filter(None,
                       (p.strip() for p in args.replicas.split(","))):
        host, _, port = part.rpartition(":")
        endpoints.append((host or "127.0.0.1", int(port)))
    out = run_load(endpoints, load_spec(args.chain),
                   clients=args.clients, reads=args.reads)
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0 if out["errors"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
