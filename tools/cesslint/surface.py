"""Surface-consistency pass: the repo's public surfaces stay coherent.

Three sub-rules, all static:

  surface-migrations   chain/checkpoint.py FORMAT_VERSION = N requires
                       MIGRATIONS to hold exactly the contiguous chain
                       {1, ..., N-1} — a version bump without its
                       migration bricks every node restoring an older
                       checkpoint (the v2..v6 ladder grew one rung per
                       format bump for exactly this reason).
  surface-rpc-docs     every `@method("name")` registered in
                       node/rpc.py must appear in docs/*.md (the
                       catalog lives in docs/rpc.md) — an undocumented
                       method is unusable and unreviewable.
  surface-metrics-help every Counter/Gauge/Histogram/LabeledCounter
                       construction carries non-empty help text — the
                       static successor of tools/lint_metrics.py
                       (`# HELP`-less metrics are dead weight on a
                       dashboard).
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

CHECKPOINT_FILE = "cess_tpu/chain/checkpoint.py"
RPC_FILE = "cess_tpu/node/rpc.py"
METRIC_CLASSES = {"Counter", "Gauge", "Histogram", "LabeledCounter"}


def run(files: list[SourceFile], docs: dict[str, str]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if sf.path == CHECKPOINT_FILE:
            out += _migrations(sf)
        if sf.path == RPC_FILE:
            out += _rpc_docs(sf, docs)
        out += _metrics_help(sf)
    return out


def _migrations(sf: SourceFile) -> list[Finding]:
    version = None
    version_line = 1
    migration_keys: set[int] = set()
    migrations_line = 1
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [
            t.id for t in node.targets if isinstance(t, ast.Name)
        ]
        if "FORMAT_VERSION" in names and isinstance(
            node.value, ast.Constant
        ):
            version = node.value.value
            version_line = node.lineno
        if "MIGRATIONS" in names and isinstance(node.value, ast.Dict):
            migrations_line = node.lineno
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, int
                ):
                    migration_keys.add(key.value)
    if version is None:
        return [Finding(
            "surface-migrations", sf.path, 1,
            "FORMAT_VERSION literal not found in checkpoint module",
        )]
    expected = set(range(1, version))
    out = []
    for missing in sorted(expected - migration_keys):
        out.append(Finding(
            "surface-migrations", sf.path, migrations_line,
            f"MIGRATIONS has no v{missing}→v{missing + 1} step — the "
            f"chain to FORMAT_VERSION={version} must be contiguous",
        ))
    for extra in sorted(migration_keys - expected):
        out.append(Finding(
            "surface-migrations", sf.path, migrations_line,
            f"MIGRATIONS key {extra} outside 1..{version - 1} — dead "
            "or future migration; bump FORMAT_VERSION with the step",
        ))
    return out


def _rpc_docs(sf: SourceFile, docs: dict[str, str]) -> list[Finding]:
    corpus = "\n".join(docs.values())
    out: list[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Name) and node.func.id == "method"
        ):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)):
            continue
        name = node.args[0].value
        if isinstance(name, str) and name not in corpus:
            out.append(Finding(
                "surface-rpc-docs", sf.path, node.lineno,
                f"RPC method {name!r} is registered but appears in no "
                "docs/*.md — add it to the docs/rpc.md catalog",
            ))
    return out


def _metrics_help(sf: SourceFile) -> list[Finding]:
    # skip the defining module (its __init__ signatures default help to
    # "") and anything outside the package
    if not sf.path.startswith("cess_tpu/") or sf.path.endswith(
        "node/metrics.py"
    ):
        return []
    out: list[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        cls = None
        if isinstance(f, ast.Attribute) and f.attr in METRIC_CLASSES:
            cls = f.attr
        elif isinstance(f, ast.Name) and f.id in METRIC_CLASSES:
            # bare names collide with collections.Counter — only treat
            # as a metric when imported from the metrics module
            if _imports_from_metrics(sf, f.id):
                cls = f.id
        if cls is None:
            continue
        help_arg = None
        if len(node.args) >= 2:
            help_arg = node.args[1]
        for kw in node.keywords:
            if kw.arg == "help_":
                help_arg = kw.value
        if help_arg is None or (
            isinstance(help_arg, ast.Constant) and not help_arg.value
        ):
            out.append(Finding(
                "surface-metrics-help", sf.path, node.lineno,
                f"{cls}(...) registered without help text — every "
                "metric must render a # HELP line",
            ))
    return out


def _imports_from_metrics(sf: SourceFile, name: str) -> bool:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("metrics")
        ):
            if any(a.name == name for a in node.names):
                return True
    return False
