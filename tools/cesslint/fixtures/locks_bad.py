# cesslint fixture — guarded-field writes off-lock, and an RPC handler
# reaching a private through the service object.  Loaded by tests under
# cess_tpu/node/rpc.py-style paths as needed.
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock

    def submit(self, k, v):
        self.entries[k] = v  # lock-guarded-write (subscript store)
        self.count += 1  # lock-guarded-write (augassign)

    def drop(self, k):
        self.entries.pop(k, None)  # lock-guarded-write (mutator)


def handler(s, args):
    s._restore(args)  # lock-rpc-private (call)
    s.rt.evm._scratch = args  # lock-rpc-private (write)
