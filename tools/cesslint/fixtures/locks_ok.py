# cesslint fixture — the three sanctioned ways to touch guarded state.
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock

    def submit(self, k, v):
        with self._lock:
            self.entries[k] = v
            self.count += 1

    def _insert(self, k, v):  # holds-lock: _lock
        self.entries[k] = v
        self.count += 1


def handler(s, args):
    with s._lock:
        s._restore(args)
        s.rt.evm._scratch = args
