# cesslint fixture — surface-pass violations.  Tests load this text
# under the checkpoint-module and rpc-module paths; each sub-rule only
# looks at its own constructs.
from cess_tpu.node.metrics import Counter


def _noop(state):
    return state


FORMAT_VERSION = 4
MIGRATIONS = {
    1: _noop,
    # v2→v3 rung missing: surface-migrations
    3: _noop,
    7: _noop,  # outside 1..3: surface-migrations (dead/future rung)
}


def method(name):
    def deco(fn):
        return fn

    return deco


@method("ghost_undocumented")  # surface-rpc-docs unless docs mention it
def ghost(s, args):
    return None


dropped = Counter("fixture_dropped")  # surface-metrics-help
named = Counter("fixture_named", "has help text")
