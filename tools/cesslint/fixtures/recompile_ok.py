# cesslint fixture — every accepted jit caching pattern.
from functools import lru_cache

import jax


def _kernel(x):
    return x + 1


_kernel_jit = jax.jit(_kernel)  # module-level: compiled once


@lru_cache(maxsize=8)
def cached_factory(shape):
    return jax.jit(_kernel)  # lru_cache owns the lifetime


def plain_factory():
    # returns WITHOUT calling — the caller owns the caching
    # (parallel/msm.py module-dict idiom)
    return jax.jit(_kernel)


def hot_entry(x):
    return _kernel_jit(x)
