# cesslint fixture — determinism-clean counterparts of det_bad.py.


def reward_share(total, n):
    return total // n


def vote_bytes(votes, canonical_json):
    return canonical_json(sorted(votes.values()))


def key_bytes(votes, canonical_json):
    # dict KEYS are safe: canonical_json sorts keys itself
    return canonical_json(votes)
