# cesslint fixture — every determinism rule fires here.  Loaded by
# tests/test_cesslint.py under a consensus-scoped path; excluded from
# load_tree so the self-run stays clean.
import os
import random
import time


def slot_now():
    return time.time()  # det-wallclock


def jitter():
    return random.random()  # det-random


def node_id():
    return os.environ["NODE_ID"]  # det-env


def reward_share(total, n):
    return total / n  # det-float (true division)


SCALE = 1.5  # det-float (literal)


def as_score(x):
    return float(x)  # det-float (call)


def vote_bytes(votes, canonical_json):
    # det-unsorted-iter: value order leaks into consensus bytes
    return canonical_json(list(votes.values()))
