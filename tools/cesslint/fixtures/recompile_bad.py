# cesslint fixture — the glv bug class: jit built and invoked per call,
# and host syncs inside a hot-section loop.
import jax
import numpy as np


def fold_per_call(f, x):
    return jax.jit(f)(x)  # jit-in-body (direct invocation)


def fold_via_local(f, x):
    g = jax.jit(f)
    return g(x)  # jit-in-body (local later called)


def stream(chunks):
    total = 0
    for c in chunks:
        total += c.sum().item()  # host-sync
        _ = np.asarray(c)  # host-sync
        _ = jax.device_get(c)  # host-sync
    return total
