"""Determinism pass: consensus-critical code must be a pure function of
chain state.

GRANDPA-style accountable safety only holds if every replica's state
transition is bit-deterministic — a replica that reads the clock, an
env var, its RNG, or float rounding into the state hash forks the
network silently.  Scope: `cess_tpu/chain/*`, `cess_tpu/consensus/*`,
and `cess_tpu/node/sync.py` (the import path that owns
`canonical_json`, THE consensus byte encoding).

Rules:
  det-wallclock     time.* / datetime.now-family calls
  det-random        any use of the `random` module (seeded fixture use
                    is justified with a pragma, e.g. chain/node.py)
  det-env           os.environ / os.getenv reads
  det-float         float literals in expressions, float() calls, and
                    `/` true division (use integer math: //, Perbill)
  det-unsorted-iter (tree-wide) .values()/.keys()/.items()/set() feeding
                    canonical_json or state_encode without sorted()
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

SCOPED_PREFIXES = ("cess_tpu/chain/", "cess_tpu/consensus/")
SCOPED_FILES = ("cess_tpu/node/sync.py",)

WALLCLOCK_TIME = {
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "sleep", "localtime", "gmtime", "ctime",
}
WALLCLOCK_DATETIME = {"now", "utcnow", "today"}

# the sinks every consensus payload flows through: block/extrinsic/vote
# signing bytes (node/sync.py canonical_json) and the checkpoint state
# hash (chain/checkpoint.py state_encode)
CANONICAL_SINKS = {"canonical_json", "state_encode"}
UNSORTED_ITERS = {"values", "keys", "items"}


def _in_scope(path: str) -> bool:
    return path.startswith(SCOPED_PREFIXES) or path in SCOPED_FILES


def _call_name(node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def run(files: list[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        out += _unsorted_iter(sf)
        if _in_scope(sf.path):
            out += _scoped_rules(sf)
    return out


def _scoped_rules(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []

    def flag(rule: str, node: ast.AST, msg: str) -> None:
        out.append(Finding(rule, sf.path, node.lineno, msg))

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name
            ):
                base, attr = f.value.id, f.attr
                if base == "time" and attr in WALLCLOCK_TIME:
                    flag(
                        "det-wallclock", node,
                        f"wall-clock call time.{attr}() in "
                        "consensus-critical code",
                    )
                elif base == "random":
                    flag(
                        "det-random", node,
                        f"random.{attr}() in consensus-critical code — "
                        "replicas each draw their own",
                    )
                elif base == "datetime" and attr in WALLCLOCK_DATETIME:
                    flag(
                        "det-wallclock", node,
                        f"wall-clock call datetime.{attr}() in "
                        "consensus-critical code",
                    )
                elif base == "os" and attr == "getenv":
                    flag(
                        "det-env", node,
                        "os.getenv() in consensus-critical code — env "
                        "vars differ per replica",
                    )
            if isinstance(f, ast.Name) and f.id == "float":
                flag(
                    "det-float", node,
                    "float() in consensus-critical code — float "
                    "rounding is not portable across replicas",
                )
        elif isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "os"
                and node.attr == "environ"
            ):
                flag(
                    "det-env", node,
                    "os.environ read in consensus-critical code — env "
                    "vars differ per replica",
                )
        elif isinstance(node, ast.Constant):
            if type(node.value) is float:
                flag(
                    "det-float", node,
                    f"float literal {node.value!r} in consensus-critical "
                    "code — use integer math",
                )
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            flag(
                "det-float", node,
                "true division `/` yields a float in consensus-critical "
                "code — use `//`",
            )
    return out


def _unsorted_iter(sf: SourceFile) -> list[Finding]:
    """Unordered-iteration results feeding a canonical sink.  dict keys
    are safe through canonical_json (sort_keys) — the hazard is VALUE
    ordering: lists built off .values()/.items()/set iteration hash in
    whatever order the container yields unless sorted() pins it."""
    out: list[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_name(node) not in CANONICAL_SINKS:
            continue
        for arg in node.args:
            for bad, label in _unordered_nodes(arg):
                out.append(Finding(
                    "det-unsorted-iter", sf.path, bad.lineno,
                    f"{label} feeds {_call_name(node)}() without "
                    "sorted() — iteration order leaks into consensus "
                    "bytes",
                ))
    return out


def _unordered_nodes(arg: ast.AST):
    """(node, label) pairs for unordered iterations under `arg` that are
    not wrapped in a sorted() call on the way up to the sink arg."""
    parents: dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(arg):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent

    def sorted_above(node: ast.AST) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.Call) and isinstance(
                cur.func, ast.Name
            ) and cur.func.id == "sorted":
                return True
            cur = parents.get(cur)
        return False

    for node in ast.walk(arg):
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in UNSORTED_ITERS
            and not node.args
        ):
            if not sorted_above(node):
                yield node, f".{node.func.attr}() iteration"
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id == "set"
        ):
            if not sorted_above(node):
                yield node, "set() construction"
