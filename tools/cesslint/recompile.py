"""Recompile-hazard pass: the glv bug class, statically.

PR 1 shipped `glv_fold` calling `jax.jit(partial(...))` per invocation
— every call re-traced and re-compiled the kernel, and the north-star
bench regressed 10x before anyone noticed.  The accepted patterns are:

  * module-level `@jax.jit` / `X = jax.jit(f)` — compiled once;
  * an `@lru_cache`d factory returning the jit (ops/rs.py);
  * a plain factory that RETURNS the jit object without calling it
    (parallel/verify.py audit_data_plane_step, ops/bigmod.py) — the
    caller owns the caching (e.g. parallel/msm.py's module-dict);

What gets flagged (`jit-in-body`): a `jax.jit(...)` constructed inside
an un-cached function body whose result is INVOKED in that same body,
directly (`jax.jit(f)(x)`) or via a local later called — i.e. a fresh
trace cache built and thrown away per call.

`host-sync` guards the streamed/fused hot sections (proof/fused.py,
ops/rs.py, parallel/verify.py): `.item()`, `np.asarray(...)`, or
`jax.device_get(...)` inside a for/while body stalls the dispatch
pipeline mid-stream — pull results once, after block_until_ready.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

HOT_FILES = (
    "cess_tpu/proof/fused.py",
    "cess_tpu/ops/rs.py",
    "cess_tpu/parallel/verify.py",
)

CACHE_DECORATORS = {"lru_cache", "cache"}


def run(files: list[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        if not sf.path.startswith("cess_tpu/"):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out += _check_function(sf, node)
        if sf.path in HOT_FILES:
            out += _host_sync(sf)
    return out


def _decorator_name(dec: ast.AST) -> str | None:
    if isinstance(dec, ast.Call):
        dec = dec.func
    if isinstance(dec, ast.Attribute):
        return dec.attr
    if isinstance(dec, ast.Name):
        return dec.id
    return None


def _is_cached(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return any(
        _decorator_name(d) in CACHE_DECORATORS for d in fn.decorator_list
    )


def _is_jax_jit(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "jit"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "jax"
    )


def _check_function(sf: SourceFile, fn) -> list[Finding]:
    if _is_cached(fn):
        return []
    out: list[Finding] = []
    parents: dict[ast.AST, ast.AST] = {}
    # walk this function only, skipping nested defs (checked separately)
    own_nodes: list[ast.AST] = []
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        own_nodes.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for child in ast.iter_child_nodes(node):
            parents[child] = node
            stack.append(child)

    # names bound to a jit object in this body
    jit_locals: set[str] = set()
    for node in own_nodes:
        if isinstance(node, ast.Assign) and _is_jax_jit(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    jit_locals.add(tgt.id)

    for node in own_nodes:
        if _is_jax_jit(node):
            parent = parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                out.append(Finding(
                    "jit-in-body", sf.path, node.lineno,
                    f"jax.jit(...) constructed and invoked per call in "
                    f"{fn.name}() — traces/compiles every invocation; "
                    "cache the jitted fn (lru_cache factory or "
                    "module level)",
                ))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in jit_locals
        ):
            out.append(Finding(
                "jit-in-body", sf.path, node.lineno,
                f"locally built jax.jit object {node.func.id!r} invoked "
                f"inside {fn.name}() — the trace cache dies with the "
                "call; cache the jitted fn (lru_cache factory or "
                "module level)",
            ))
    return out


def _host_sync(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    for loop in ast.walk(sf.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "item"
                and not node.args
            ):
                out.append(Finding(
                    "host-sync", sf.path, node.lineno,
                    ".item() inside a hot-section loop — host sync "
                    "stalls the dispatch stream; pull once after "
                    "block_until_ready",
                ))
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and (
                    (f.value.id == "np" and f.attr == "asarray")
                    or (f.value.id == "jax" and f.attr == "device_get")
                )
            ):
                out.append(Finding(
                    "host-sync", sf.path, node.lineno,
                    f"{f.value.id}.{f.attr}(...) inside a hot-section "
                    "loop — device→host pull per iteration kills the "
                    "transfer/compute overlap",
                ))
    return out
