"""Lock-discipline pass: annotation-driven guarded-field checking.

The bls12_381 coeff-cache KeyError was a check-then-act race on a field
shared between the authoring loop and RPC/gossip threads.  This pass
makes the locking contract machine-checked via two comment annotations:

  self.blocks = {}          # guarded-by: _lock
      declares the field is only touched under `with self.<lock>`;
  def _commit_block(...):   # holds-lock: _lock
      declares the method is only ever entered with the lock already
      held (an internal helper below a locked public entry point), so
      its writes need no lexical `with`.

Rules:
  lock-guarded-write  a write (assign / augassign / del / subscript
                      store) or mutator call (.add/.append/.pop/...)
                      on a guarded `self.<field>` outside `with
                      self.<lock>`, in any method of the annotated
                      class other than __init__ / holds-lock methods.
  lock-rpc-private    node/rpc.py handlers run on server threads; a
                      call to an underscore-private attribute reachable
                      through the closed-over service object (`s.rt.evm.
                      _restore(...)`) bypasses the locked public API —
                      require `with s._lock` or go through a public
                      method.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "push", "remove",
    "setdefault", "update",
}

RPC_FILE = "cess_tpu/node/rpc.py"


def run(files: list[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                out += _check_class(sf, node)
        if sf.path == RPC_FILE:
            out += _check_rpc(sf)
    return out


# --------------------------------------------------- guarded-field core


def _guarded_fields(sf: SourceFile, cls: ast.ClassDef) -> dict[str, str]:
    """{field: lock} from `# guarded-by:` comments on self.X = ... lines
    anywhere in the class body (normally __init__)."""
    fields: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        lock = sf.guarded.get(node.lineno) or sf.guarded.get(
            getattr(node.value, "end_lineno", node.lineno) or node.lineno
        )
        if lock is None:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for tgt in targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                fields[tgt.attr] = lock
    return fields


def _self_field(node: ast.AST) -> str | None:
    """The first attribute on a chain rooted at `self`, descending
    through attributes/subscripts: self.blocks[h].x → 'blocks'."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        base = node.value
        if (
            isinstance(node, ast.Attribute)
            and isinstance(base, ast.Name)
            and base.id == "self"
        ):
            return node.attr
        node = base
    return None


def _with_locks(stack: list[ast.AST], root: str = "self") -> set[str]:
    """Lock attrs held lexically: every `with <root>.<attr>` on the
    ancestor stack."""
    held: set[str] = set()
    for node in stack:
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == root
            ):
                held.add(expr.attr)
    return held


def _check_class(sf: SourceFile, cls: ast.ClassDef) -> list[Finding]:
    fields = _guarded_fields(sf, cls)
    if not fields:
        return []
    out: list[Finding] = []
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if method.name == "__init__":
            continue  # construction happens-before publication
        held_always = sf.holds.get(method.lineno) or sf.holds.get(
            method.lineno - 1
        )
        out += _check_method(sf, cls, method, fields, held_always)
    return out


def _check_method(sf, cls, method, fields, held_always) -> list[Finding]:
    out: list[Finding] = []

    def flag(node: ast.AST, field: str, what: str) -> None:
        out.append(Finding(
            "lock-guarded-write", sf.path, node.lineno,
            f"{cls.name}.{method.name}: {what} guarded field "
            f"self.{field} outside `with self.{fields[field]}` "
            "(annotate the method `# holds-lock:` if callers hold it)",
        ))

    def visit(node: ast.AST, stack: list[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node is not method
        ):
            return  # nested defs get their own discipline via callers
        held = _with_locks(stack)

        def protected(field: str) -> bool:
            return held_always == fields[field] or fields[field] in held

        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                field = _self_field(tgt)
                if field in fields and not protected(field):
                    flag(tgt, field, "write to")
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                field = _self_field(tgt)
                if field in fields and not protected(field):
                    flag(tgt, field, "del on")
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in MUTATORS:
                field = _self_field(node.func.value)
                if field in fields and not protected(field):
                    flag(node, field, f".{node.func.attr}() on")
        for child in ast.iter_child_nodes(node):
            visit(child, stack + [node])

    for stmt in method.body:
        visit(stmt, [])
    return out


# ------------------------------------------------------ rpc.py handlers


def _check_rpc(sf: SourceFile) -> list[Finding]:
    """RPC handlers close over `s = self.service` and run on server
    threads.  Private (`_`-prefixed) attribute calls through `s` reach
    service/runtime internals without the locked public API."""
    out: list[Finding] = []

    def service_rooted(node: ast.AST) -> bool:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return isinstance(node, ast.Name) and node.id == "s"

    def visit(node: ast.AST, stack: list[ast.AST]) -> None:
        held = _with_locks(stack, root="s")
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr.startswith("_")
            and service_rooted(node.func.value)
            and "_lock" not in held
        ):
            out.append(Finding(
                "lock-rpc-private", sf.path, node.lineno,
                f"RPC thread calls private {node.func.attr}() through "
                "the service outside `with s._lock` — use a public "
                "method or take the lock",
            ))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                if isinstance(
                    tgt, (ast.Attribute, ast.Subscript)
                ) and service_rooted(tgt) and "_lock" not in held:
                    out.append(Finding(
                        "lock-rpc-private", sf.path, tgt.lineno,
                        "RPC thread writes service state outside "
                        "`with s._lock`",
                    ))
        for child in ast.iter_child_nodes(node):
            visit(child, stack + [node])

    visit(sf.tree, [])
    return out
