"""CLI: `python -m tools.cesslint` — the CI lint gate.

Exit 0 when every finding is pragma'd or baselined, 1 otherwise.
Never imports jax or cess_tpu: the gate runs on a bare checkout in
seconds, before any test job spends minutes compiling kernels.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from . import PASSES
from .core import (
    REPO_ROOT,
    load_baseline,
    load_tree,
    render_baseline,
    run_tree,
)

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="cesslint",
        description="consensus-determinism / recompile / lock-discipline"
        " / surface static analysis (docs/static-analysis.md)",
    )
    ap.add_argument(
        "--root", default=str(REPO_ROOT),
        help="repo root to analyze (default: this checkout)",
    )
    ap.add_argument(
        "--passes", default=",".join(PASSES),
        help=f"comma-separated subset of {','.join(PASSES)}",
    )
    ap.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE),
        help="baseline file of grandfathered findings",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report everything unsuppressed)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    unknown = [p for p in passes if p not in PASSES]
    if unknown:
        print(f"cesslint: unknown pass(es): {', '.join(unknown)}",
              file=sys.stderr)
        return 2

    files, docs = load_tree(args.root)
    baseline: set[str] | None = None
    if not args.no_baseline and not args.write_baseline:
        path = Path(args.baseline)
        if path.exists():
            try:
                baseline = load_baseline(path)
            except ValueError as exc:
                print(f"cesslint: {exc}", file=sys.stderr)
                return 2

    kept, suppressed = run_tree(
        files, docs, passes=passes, baseline=baseline
    )

    if args.write_baseline:
        baselineable = [
            f for f in kept if not f.rule.startswith("det-")
            and f.rule != "pragma"
        ]
        Path(args.baseline).write_text(render_baseline(baselineable))
        refused = len(kept) - len(baselineable)
        print(
            f"cesslint: wrote {len(baselineable)} finding(s) to "
            f"{args.baseline}"
            + (f" ({refused} det-*/pragma finding(s) refused — fix or "
               f"pragma those)" if refused else "")
        )
        return 0

    for f in kept:
        print(f.render())
    dt = time.perf_counter() - t0
    status = "FAIL" if kept else "ok"
    print(
        f"cesslint: {status} — {len(files)} files, "
        f"{'/'.join(passes)}: {len(kept)} finding(s), "
        f"{len(suppressed)} suppressed (pragma/baseline), {dt:.2f}s"
    )
    if kept:
        print(
            "fix the code, add `# cesslint: allow[rule] reason`, or "
            "(non-determinism rules only) baseline with "
            "--write-baseline; see docs/static-analysis.md",
        )
    return 1 if kept else 0


if __name__ == "__main__":
    raise SystemExit(main())
