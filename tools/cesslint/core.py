"""cesslint core: source loading, pragmas, annotations, baseline, runner.

The unit of analysis is a SourceFile — path, text, parsed AST, and the
comment side-channel (pragmas plus the lock-discipline annotation
vocabulary), extracted once with tokenize so every pass shares it.

Suppression model, in order of application:

  1. `# cesslint: allow[rule] reason` on the finding's line (or the
     line directly above) suppresses that rule there.  The reason is
     mandatory — a bare pragma is itself a finding, and so is a pragma
     that suppresses nothing (rule id `pragma`).
  2. The committed baseline (tools/cesslint/baseline.txt) grandfathers
     findings by (rule, path, message) — no line numbers, so unrelated
     edits don't churn it.  Determinism findings may NOT be baselined:
     replicas either agree bit-for-bit or fork, so `det-*` entries are
     rejected at load time (fix the code or justify with a pragma).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

PRAGMA_RE = re.compile(
    r"#\s*cesslint:\s*allow\[([A-Za-z0-9_\-, ]+)\]\s*(.*)"
)
GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_]\w*)")

# Only determinism rules are barred from the baseline; every other
# pass may carry grandfathered findings while they're burned down.
UNBASELINEABLE_PREFIX = "det-"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def baseline_key(self) -> str:
        return f"{self.rule}\t{self.path}\t{self.message}"


@dataclass
class Pragma:
    line: int
    rules: tuple[str, ...]
    reason: str
    used: bool = False


@dataclass
class SourceFile:
    path: str  # repo-relative, forward slashes
    text: str
    tree: ast.AST
    pragmas: dict[int, Pragma] = field(default_factory=dict)
    guarded: dict[int, str] = field(default_factory=dict)  # line -> lock
    holds: dict[int, str] = field(default_factory=dict)  # line -> lock

    @classmethod
    def from_text(cls, path: str, text: str) -> "SourceFile":
        tree = ast.parse(text, filename=path)
        sf = cls(path=path, text=text, tree=tree)
        raw_lines = text.splitlines()
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            m = PRAGMA_RE.search(tok.string)
            if m:
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                pragma = Pragma(line, rules, m.group(2).strip())
                sf.pragmas[line] = pragma
                # a pragma opening a full-line comment block (possibly
                # a multi-line justification) covers the first code
                # line below the block
                target = line
                while target <= len(raw_lines) and raw_lines[
                    target - 1
                ].lstrip().startswith("#"):
                    target += 1
                sf.pragmas.setdefault(target, pragma)
            m = GUARDED_RE.search(tok.string)
            if m:
                sf.guarded[line] = m.group(1)
            m = HOLDS_RE.search(tok.string)
            if m:
                sf.holds[line] = m.group(1)
        return sf

    def pragma_for(self, line: int) -> Pragma | None:
        """Pragma on the line itself, on the line directly above, or
        opening the comment block directly above."""
        return self.pragmas.get(line) or self.pragmas.get(line - 1)


# ------------------------------------------------------------ tree load


def _iter_py(root: Path):
    for sub in ("cess_tpu", "tools"):
        base = root / sub
        if base.is_dir():
            yield from sorted(base.rglob("*.py"))
    yield from sorted(root.glob("*.py"))


def load_tree(root: Path | str = REPO_ROOT):
    """(files, docs): every repo .py outside tests/ parsed, plus the
    docs/*.md corpus the surface pass greps for RPC coverage."""
    root = Path(root)
    files: list[SourceFile] = []
    for p in _iter_py(root):
        rel = p.relative_to(root).as_posix()
        if rel.startswith("tools/cesslint/fixtures/"):
            continue
        try:
            files.append(SourceFile.from_text(rel, p.read_text()))
        except SyntaxError as exc:
            raise RuntimeError(f"cesslint: cannot parse {rel}: {exc}")
    docs = {
        p.relative_to(root).as_posix(): p.read_text()
        for p in sorted((root / "docs").glob("*.md"))
    }
    return files, docs


# ------------------------------------------------------------- baseline


def load_baseline(path: Path | str) -> set[str]:
    keys: set[str] = set()
    text = Path(path).read_text()
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        rule = line.split("\t", 1)[0]
        if rule.startswith(UNBASELINEABLE_PREFIX):
            raise ValueError(
                f"{path}:{ln}: determinism findings may not be "
                f"baselined (rule {rule}) — fix the code or add a "
                f"justified pragma"
            )
        keys.add(line)
    return keys


def render_baseline(findings: list[Finding]) -> str:
    lines = [
        "# cesslint baseline — grandfathered findings, one per line as",
        "# rule<TAB>path<TAB>message.  det-* rules are refused at load",
        "# time: determinism findings must be fixed or pragma'd, never",
        "# baselined.  Burn this file down, don't grow it.",
    ]
    for f in sorted(set(findings), key=lambda f: (f.path, f.rule, f.message)):
        lines.append(f.baseline_key())
    return "\n".join(lines) + "\n"


# -------------------------------------------------------------- runner


def run_tree(
    files: list[SourceFile],
    docs: dict[str, str] | None = None,
    passes: tuple[str, ...] = ("determinism", "recompile", "locks", "surface"),
    baseline: set[str] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """Run the selected passes.  Returns (kept, suppressed): kept are
    the findings that should fail the build (pragma/`pragma`-rule
    findings included), suppressed are those silenced by a pragma or
    the baseline."""
    from . import determinism, locks, recompile, surface

    raw: list[Finding] = []
    if "determinism" in passes:
        raw += determinism.run(files)
    if "recompile" in passes:
        raw += recompile.run(files)
    if "locks" in passes:
        raw += locks.run(files)
    if "surface" in passes:
        raw += surface.run(files, docs or {})

    by_path = {f.path: f for f in files}
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        sf = by_path.get(f.path)
        pragma = sf.pragma_for(f.line) if sf else None
        if pragma and f.rule in pragma.rules:
            pragma.used = True
            suppressed.append(f)
        elif baseline and f.baseline_key() in baseline:
            suppressed.append(f)
        else:
            kept.append(f)

    # pragma hygiene: every suppression carries a reason, every pragma
    # suppresses something, every rule name is real.  Unused-pragma
    # checks only consider rules whose pass ran — a det-* pragma is
    # not "unused" during a locks-only invocation.
    known = set(ALL_RULES)
    active = {
        r for p in passes for r in RULES_OF_PASS.get(p, ())
    }
    seen_pragmas: set[int] = set()
    for sf in files:
        for pragma in sf.pragmas.values():
            if id(pragma) in seen_pragmas:
                continue  # block-propagated alias of the same pragma
            seen_pragmas.add(id(pragma))
            for rule in pragma.rules:
                if rule not in known:
                    kept.append(Finding(
                        "pragma", sf.path, pragma.line,
                        f"unknown rule {rule!r} in allow[...] pragma",
                    ))
            if not pragma.reason:
                kept.append(Finding(
                    "pragma", sf.path, pragma.line,
                    "allow[...] pragma without a reason — justify the "
                    "suppression",
                ))
            if not pragma.used and pragma.rules and set(
                pragma.rules
            ) <= known and set(pragma.rules) & active:
                kept.append(Finding(
                    "pragma", sf.path, pragma.line,
                    f"unused allow[{','.join(pragma.rules)}] pragma — "
                    "suppresses nothing on this line",
                ))
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept, suppressed


RULES_OF_PASS = {
    "determinism": (
        "det-wallclock", "det-random", "det-env", "det-float",
        "det-unsorted-iter",
    ),
    "recompile": ("jit-in-body", "host-sync"),
    "locks": ("lock-guarded-write", "lock-rpc-private"),
    "surface": (
        "surface-migrations", "surface-rpc-docs", "surface-metrics-help",
    ),
}

ALL_RULES = tuple(
    r for rules in RULES_OF_PASS.values() for r in rules
) + ("pragma",)
