"""cesslint: consensus-determinism, JAX-recompile, lock-discipline and
surface-consistency static analysis for the cess-tpu tree.

Pure-AST analyzer — importing this package must never import jax or
cess_tpu (the CI lint job runs it in seconds on a bare checkout; a
fixture test asserts `jax` stays out of sys.modules).  See
docs/static-analysis.md for the rule catalog and pragma syntax.
"""

from .core import Finding, SourceFile, load_tree, run_tree  # noqa: F401

PASSES = ("determinism", "recompile", "locks", "surface")
