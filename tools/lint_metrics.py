"""Metrics lint: every registered metric must carry non-empty help text.

CI gate (build-and-test.yml): constructs the full metric surface — a
networked NodeService + SyncManager registry and the process-wide
proof-stage registry — and fails if any metric would render without a
# HELP line.  A nameless metric is unusable from a dashboard; this
keeps the exposition self-describing as the surface grows.
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")


def collect_registries():
    import tempfile

    from cess_tpu.node.chain_spec import local_spec
    from cess_tpu.node.service import NodeService
    from cess_tpu.node.store import BlockStore
    from cess_tpu.node.sync import SyncManager
    from cess_tpu.ops.rs import rs_stage_registry
    from cess_tpu.proof.xla_backend import proof_stage_registry

    service = NodeService(local_spec(), authority="alice")
    SyncManager(service, peers=[("127.0.0.1", 1)])
    # the store registers its cess_store_* families into the service
    # registry exactly as `--data-dir` wiring does (node/cli.py)
    with tempfile.TemporaryDirectory() as d:
        BlockStore(d, registry=service.registry).close()
    return {
        "service": service.registry,
        "proof": proof_stage_registry(),
        "rs": rs_stage_registry(),
    }


def main() -> int:
    bad = []
    total = 0
    for origin, registry in collect_registries().items():
        for metric in registry.metrics():
            total += 1
            if not getattr(metric, "help", ""):
                bad.append(f"{origin}:{metric.name}")
    if bad:
        print("metrics missing help text:", file=sys.stderr)
        for name in bad:
            print(f"  {name}", file=sys.stderr)
        return 1
    print(f"metrics lint: {total} metrics, all with help text")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
