"""Metrics lint — thin shim over cesslint's surface pass.

Historically this script imported the node stack, instantiated the full
metric surface, and checked every registered metric for help text.  That
check is now the `surface-metrics-help` rule in tools/cesslint (pure
AST, no cess_tpu import, so it also covers registries the old runtime
walk couldn't reach without JAX).  This entry point is kept because CI
and docs/observability.md reference `python tools/lint_metrics.py`; it
delegates to the surface pass and preserves the exit-code contract.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from tools.cesslint import load_tree, run_tree

    files, docs = load_tree()
    kept, _ = run_tree(files, docs, passes=("surface",))
    kept = [f for f in kept if f.rule == "surface-metrics-help"]
    if kept:
        print("metrics missing help text:", file=sys.stderr)
        for f in kept:
            print(f"  {f.render()}", file=sys.stderr)
        return 1
    print("metrics lint: ok (cesslint surface-metrics-help)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
