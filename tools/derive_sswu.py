"""Derive the RFC 9380 G1 SSWU isogeny for BLS12-381 and emit it as
cess_tpu/ops/_sswu_g1.py.

The simplified-SWU map for BLS12-381 G1 targets an auxiliary curve
E': y^2 = x^3 + A'x + B' that is 11-isogenous to E: y^2 = x^3 + 4,
followed by an 11-isogeny E' -> E.  The RFC publishes the isogeny as ~50
large hex constants; this script derives them from the ciphersuite
parameters (A', B', Z) instead of transcribing them:

  1. build the 11-division polynomial psi_11 of E' (degree 60) over Fp;
  2. split off the rational kernel polynomial(s) h (degree 5) with
     gcd(x^p - x, psi_11) plus an equal-degree split when both order-11
     subgroups are rational;
  3. run Velu's formulas symbolically: the kernel-root sums
     sum_i tau(x_i) * h(x)/(x - x_i) are computed as (tau * h') mod h
     (interpolation at the roots), so no root extraction is needed; this
     yields the codomain E2: y^2 = x^3 + B2 and the normalized maps
       phi_x = N/h^2,  phi_y = y * d(phi_x)/dx;
  4. scale E2 onto E with (x, y) -> (x/w^2, y/w^3), w^6 = B2/4 (sixth
     roots via sqrt + a 3-Sylow discrete-log cube root);
  5. the remaining finite ambiguity (<= 2 kernels x 6 roots w) is
     resolved by the IC known-answer vectors carried by the reference
     (/root/reference/utils/verify-bls-signatures/tests/tests.rs:96-127):
     the unique candidate that re-generates the expected signature from
     the published secret key is emitted.

Everything downstream of (A', B', Z) is derived, and the KAT pins the
whole pipeline (expand_message_xmd, SSWU, isogeny, cofactor clearing,
point compression) to 128-bit strength.

Run:  python tools/derive_sswu.py     (~1 minute; writes
      cess_tpu/ops/_sswu_g1.py and prints the selected normalization)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cess_tpu.ops import bls12_381 as bls  # noqa: E402
from cess_tpu.ops.bls12_381 import P  # noqa: E402

# RFC 9380 §8.8.1 ciphersuite parameters for BLS12381G1_XMD:SHA-256_SSWU_RO
# (KAT-verified along with everything derived from them).
A_PRIME = int(
    "0x144698a3b8e9433d693a02c96d4982b0ea985383ee66a8d8e8981aef"
    "d881ac98936f8da0e0f97f5cf428082d584c1d",
    16,
)
B_PRIME = int(
    "0x12e2908d11688030018b12e8753eee3b2016c1f0f24f4070a0b9c14f"
    "cef35ef55a23215a316ceaa5d1cc48e98e172be0",
    16,
)
Z_SSWU = 11

IC_DST = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_"

# RFC 9380 §8.8.1 effective cofactor for G1: h_eff = 1 − z (NOT the full
# cofactor (z−1)²/3 — they differ by a scalar multiple on the r-torsion).
H_EFF = 0xD201000000010001

# KAT: "generates_expected_signature" from the reference tests
# (utils/verify-bls-signatures/tests/tests.rs:114-127).
KAT_SK = int(
    "6f3977f6051e184b2c412daa1b5c0115ef7ab347cac8d808ffa2c26bd0658243", 16
)
KAT_MSG = bytes.fromhex(
    "50484522ad8aede64ec7f86b9273b7ed3940481acf93cdd40a2b77f2be2734a1"
    "4012b2492b6363b12adaeaf055c573e4611b085d2e0fe2153d72453a95eaebf3"
    "50ac3ba6a26ba0bc79f4c0bf5664dfdf5865f69f7fc6b58ba7d068e8"
)
KAT_SIG = bytes.fromhex(
    "8f7ad830632657f7b3eae17fd4c3d9ff5c13365eea8d33fd0a1a6d8fbebc5152"
    "e066bb0ad61ab64e8a8541c8e3f96de9"
)


# ---------------------------------------------------------------- Fp polys
# Dense little-endian coefficient lists over Fp.


def ptrim(f):
    while f and f[-1] == 0:
        f.pop()
    return f


def padd(f, g):
    n = max(len(f), len(g))
    out = [0] * n
    for i, c in enumerate(f):
        out[i] = c
    for i, c in enumerate(g):
        out[i] = (out[i] + c) % P
    return ptrim(out)


def psub(f, g):
    n = max(len(f), len(g))
    out = [0] * n
    for i, c in enumerate(f):
        out[i] = c
    for i, c in enumerate(g):
        out[i] = (out[i] - c) % P
    return ptrim(out)


def pmul(f, g):
    if not f or not g:
        return []
    out = [0] * (len(f) + len(g) - 1)
    for i, a in enumerate(f):
        if a:
            for j, b in enumerate(g):
                out[i + j] = (out[i + j] + a * b) % P
    return ptrim(out)


def pscale(f, c):
    c %= P
    return ptrim([a * c % P for a in f])


def pmod(f, g):
    f = list(f)
    ginv = pow(g[-1], P - 2, P)
    dg = len(g) - 1
    while f and len(f) - 1 >= dg:
        c = f[-1] * ginv % P
        shift = len(f) - 1 - dg
        for i, b in enumerate(g):
            f[shift + i] = (f[shift + i] - c * b) % P
        ptrim(f)
    return f


def pgcd(f, g):
    while g:
        f, g = g, pmod(f, g)
    if f:
        f = pscale(f, pow(f[-1], P - 2, P))  # monic
    return f


def pdiv_exact(f, g):
    f = list(f)
    out = [0] * (len(f) - len(g) + 1)
    ginv = pow(g[-1], P - 2, P)
    while f and len(f) >= len(g):
        c = f[-1] * ginv % P
        shift = len(f) - len(g)
        out[shift] = c
        for i, b in enumerate(g):
            f[shift + i] = (f[shift + i] - c * b) % P
        ptrim(f)
    assert not f, "division not exact"
    return ptrim(out)


def pdiff(f):
    return ptrim([(i * c) % P for i, c in enumerate(f)][1:])


def ppowmod(base, e, mod):
    result = [1]
    base = pmod(list(base), mod)
    while e:
        if e & 1:
            result = pmod(pmul(result, base), mod)
        base = pmod(pmul(base, base), mod)
        e >>= 1
    return result


def peval(f, x):
    acc = 0
    for c in reversed(f):
        acc = (acc * x + c) % P
    return acc


# ------------------------------------------------- division polynomial


def division_poly_11(A, B):
    """psi_11 as an x-polynomial, via the standard recurrences with
    y^2 -> F = x^3 + Ax + B.  psi_n is stored as an x-poly carrying an
    implicit factor y for even n (psi_2 = 2y is stored as [2])."""
    F = [B % P, A % P, 0, 1]

    psi: dict[int, list[int]] = {
        0: [],
        1: [1],
        2: [2],
        3: ptrim([(-A * A) % P, (12 * B) % P, (6 * A) % P, 0, 3]),
        4: pscale(
            ptrim(
                [
                    (-8 * B * B - A * A * A) % P,
                    (-4 * A * B) % P,
                    (-5 * A * A) % P,
                    (20 * B) % P,
                    (5 * A) % P,
                    0,
                    1,
                ]
            ),
            4,
        ),
    }

    def yexp(n):
        return 1 if n % 2 == 0 else 0

    def get(n):
        if n in psi:
            return psi[n]
        m = n // 2
        if n % 2 == 1:
            # psi_{2m+1} = psi_{m+2} psi_m^3 − psi_{m−1} psi_{m+1}^3
            a = pmul(get(m + 2), pmul(get(m), pmul(get(m), get(m))))
            b = pmul(
                get(m - 1), pmul(get(m + 1), pmul(get(m + 1), get(m + 1)))
            )
            ya = yexp(m + 2) + 3 * yexp(m)
            yb = yexp(m - 1) + 3 * yexp(m + 1)
            assert ya % 2 == 0 and yb % 2 == 0, (n, ya, yb)
            for _ in range(ya // 2):
                a = pmul(a, F)
            for _ in range(yb // 2):
                b = pmul(b, F)
            out = psub(a, b)
        else:
            # psi_{2m} = psi_m (psi_{m+2} psi_{m−1}² − psi_{m−2} psi_{m+1}²)/(2y)
            a = pmul(get(m + 2), pmul(get(m - 1), get(m - 1)))
            b = pmul(get(m - 2), pmul(get(m + 1), get(m + 1)))
            ya = yexp(m + 2) + 2 * yexp(m - 1)
            yb = yexp(m - 2) + 2 * yexp(m + 1)
            assert ya == yb, (n, ya, yb)
            # y-power of psi_m·(A−B) is total; after /2y the stored poly
            # keeps one implicit y (n even), so F-substitute the rest.
            total = ya + yexp(m)
            assert total >= 2 and total % 2 == 0, (n, total)
            inner = psub(a, b)
            for _ in range((total - 2) // 2):
                inner = pmul(inner, F)
            out = pscale(pmul(get(m), inner), pow(2, P - 2, P))
        psi[n] = out
        return out

    f11 = get(11)
    assert len(f11) - 1 == 60, f"psi_11 degree {len(f11) - 1}, want 60"
    assert f11[-1] % P == 11, "psi_11 leading coefficient must be 11"
    return f11


# ------------------------------------------------- kernel extraction


def rational_kernels(A, B):
    """Degree-5 kernel polynomials of the rational 11-isogenies from
    y^2 = x^3 + Ax + B (the x-coordinates of each order-11 subgroup)."""
    psi11 = division_poly_11(A, B)
    psi11 = pscale(psi11, pow(psi11[-1], P - 2, P))  # monic
    xp = ppowmod([0, 1], P, psi11)
    lin = pgcd(psub(xp, [0, 1]), psi11)
    d = len(lin) - 1
    if d == 0:
        raise AssertionError(
            "no rational 11-torsion x-coordinates; parameter transcription wrong?"
        )
    if d == 5:
        return [lin]
    if d == 10:
        # two rational subgroups: equal-degree split (Cantor–Zassenhaus)
        import random as _random

        rng = _random.Random(0xCE55)
        for _ in range(64):
            delta = rng.randrange(P)
            probe = ppowmod([delta, 1], (P - 1) // 2, lin)
            g = pgcd(psub(probe, [1]), lin)
            if 0 < len(g) - 1 < 10:
                h1 = pgcd(g, lin) if len(g) - 1 == 5 else None
                if h1 is None:
                    # uneven split: refine by gcd with the cofactor
                    part = g
                    other = pdiv_exact(lin, part)
                    cands = [part, other]
                    fives = [c for c in cands if len(c) - 1 == 5]
                    if len(fives) == 2:
                        return fives
                    continue
                h2 = pdiv_exact(lin, h1)
                if len(h2) - 1 == 5:
                    return [h1, h2]
        raise AssertionError("equal-degree split did not converge")
    raise AssertionError(f"unexpected rational x-coordinate count {d}")


# ------------------------------------------------- Velu


def velu(A, B, h):
    """Velu's formulas with kernel polynomial h (degree 5, monic):
    returns (A2, B2, x_num, x_den, y_num, y_den) where
      phi_x = x_num/x_den,  phi_y = y · y_num/y_den  (normalized).
    """
    hp = pdiff(h)

    def trace(tau):
        # sum_i tau(x_i)·h(x)/(x−x_i) = (tau·h') mod h  (degree < 5
        # interpolation of tau(x_i)·h'(x_i) at the kernel roots)
        return pmod(pmul(tau, hp), h)

    # per x-coordinate (each ±pair of kernel points counted once):
    #   t_i = 2(3 x_i² + A),  u_i = 4(x_i³ + A x_i + B)
    tau_t = pscale([A % P, 0, 3], 2)
    tau_u = pscale([B % P, A % P, 0, 1], 4)

    # power sums of the kernel x-coordinates from h's coefficients
    e1 = (-h[4]) % P
    e2 = h[3] % P
    e3 = (-h[2]) % P
    p1 = e1
    p2 = (e1 * p1 - 2 * e2) % P
    p3 = (e1 * p2 - e2 * p1 + 3 * e3) % P
    sum_t = (6 * p2 + 10 * A) % P
    sum_w = (10 * p3 + 6 * A * p1 + 20 * B) % P
    A2 = (A - 5 * sum_t) % P
    B2 = (B - 7 * sum_w) % P

    # phi_x = x + T/h + (U h' − U' h)/h² = N/h²
    T = trace(tau_t)
    U = trace(tau_u)
    h2 = pmul(h, h)
    N = padd(
        pmul([0, 1], h2),
        padd(pmul(T, h), psub(pmul(U, hp), pmul(pdiff(U), h))),
    )

    # phi_y = y·d(phi_x)/dx = y·(N' h − 2 N h')/h³
    y_num = psub(pmul(pdiff(N), h), pscale(pmul(N, hp), 2))
    y_den = pmul(h2, h)
    return A2, B2, N, h2, y_num, y_den


# ------------------------------------------------- roots in Fp


def sqrt_fp(a):
    a %= P
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a else None


def cbrt_fp(a):
    """Cube root via discrete log in the 3-Sylow subgroup of Fp*."""
    a %= P
    if a == 0:
        return 0
    if pow(a, (P - 1) // 3, P) != 1:
        return None
    s, t = 0, P - 1
    while t % 3 == 0:
        s, t = s + 1, t // 3
    g = 2
    while pow(g, (P - 1) // 3, P) == 1:
        g += 1
    e = pow(g, t, P)  # generates the 3-Sylow subgroup, order 3^s
    order = 3**s
    # k with e^k = a^t  (base-3 digits, s is tiny)
    target = pow(a, t, P)
    k = 0
    for j in range(s):
        probe = target * pow(e, (order - k) % order, P) % P
        if pow(probe, 3 ** (s - 1 - j), P) != 1:
            for m in (1, 2):
                trial = (k + m * 3**j) % order
                probe2 = target * pow(e, (order - trial) % order, P) % P
                if pow(probe2, 3 ** (s - 1 - j), P) == 1:
                    k = trial
                    break
            else:
                return None
    if k % 3 != 0:
        return None
    c = a * pow(e, (order - k) % order, P) % P  # order divides t, 3 ∤ t
    r = pow(c, pow(3, -1, t), P) * pow(e, k // 3, P) % P
    return r if pow(r, 3, P) == a else None


def sixth_roots(a):
    """All w in Fp with w^6 = a."""
    a %= P
    out = set()
    s = sqrt_fp(a)
    if s is None:
        return []
    omega = None
    g = 2
    while True:
        omega = pow(g, (P - 1) // 3, P)
        if omega != 1:
            break
        g += 1
    for sr in (s, P - s):
        c = cbrt_fp(sr)
        if c is None:
            continue
        for w in (c, c * omega % P, c * omega % P * omega % P):
            if pow(w, 6, P) == a:
                out.add(w)
    return sorted(out)


# ------------------------------------------------- SSWU + selection


def sswu_xy(u, A, B, Z):
    """RFC 9380 §6.6.2 simplified SWU onto y² = x³ + Ax + B (A·B ≠ 0)."""
    u %= P
    tv = Z * u % P * u % P
    tv2 = (tv * tv + tv) % P
    if tv2 == 0:
        x1 = B * pow(Z * A % P, P - 2, P) % P
    else:
        x1 = (-B) % P * pow(A, P - 2, P) % P * (1 + pow(tv2, P - 2, P)) % P
    gx1 = (x1 * x1 % P * x1 + A * x1 + B) % P
    y1 = sqrt_fp(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x = tv * x1 % P
        gx2 = (x * x % P * x + A * x + B) % P
        y = sqrt_fp(gx2)
        assert y is not None, "SSWU: neither candidate is square"
    if (y & 1) != (u & 1):  # sgn0 alignment
        y = P - y
    return x, y


def make_apply(xn, xd, yn, yd):
    def apply(x, y):
        den = peval(xd, x)
        if den == 0:
            return None  # kernel x-coordinate → maps to infinity
        X = peval(xn, x) * pow(den, P - 2, P) % P
        Y = y * peval(yn, x) % P * pow(peval(yd, x), P - 2, P) % P
        return X, Y

    return apply


def hash_to_g1_with(apply_iso, msg, dst):
    us = bls.hash_to_field_fp(msg, dst, 2)
    pts = []
    for u in us:
        x, y = sswu_xy(u, A_PRIME, B_PRIME, Z_SSWU)
        out = apply_iso(x, y)
        assert out is not None, "hash input hit the isogeny kernel"
        pts.append(bls.G1Point(out[0], out[1]))
    return (pts[0] + pts[1])._mul_raw(H_EFF)


def main():
    print("deriving rational 11-isogeny kernels of E' ...", flush=True)
    kernels = rational_kernels(A_PRIME, B_PRIME)
    print(f"  {len(kernels)} rational kernel(s)")

    candidates = []
    for ki, h in enumerate(kernels):
        A2, B2, x_num, x_den, y_num, y_den = velu(A_PRIME, B_PRIME, h)
        if A2 != 0:
            print(f"  kernel {ki}: codomain A2 != 0 (j != 0), skipped")
            continue
        for w in sixth_roots(B2 * pow(4, P - 2, P) % P):
            # fold the E2→E scaling (x/w², y/w³) into the maps
            xn = pscale(x_num, pow(pow(w, 2, P), P - 2, P))
            yn = pscale(y_num, pow(pow(w, 3, P), P - 2, P))
            candidates.append((ki, w, xn, x_den, yn, y_den))
    print(f"  {len(candidates)} candidate normalizations")

    selected = None
    for ki, w, xn, xd, yn, yd in candidates:
        hpt = hash_to_g1_with(make_apply(xn, xd, yn, yd), KAT_MSG, IC_DST)
        if hpt.mul(KAT_SK).to_bytes() == KAT_SIG:
            selected = (ki, w, xn, xd, yn, yd)
            break
    assert selected is not None, "no normalization reproduces the IC KAT"
    ki, w, xn, xd, yn, yd = selected
    print(f"  selected kernel {ki}, scale w = {hex(w)[:20]}…")

    out_path = os.path.join(
        os.path.dirname(__file__), "..", "cess_tpu", "ops", "_sswu_g1.py"
    )

    def fmt(coeffs):
        rows = ",\n    ".join(hex(c) for c in coeffs)
        return f"[\n    {rows},\n]"

    with open(out_path, "w") as f:
        f.write(
            '"""RFC 9380 SSWU parameters + 11-isogeny for BLS12-381 G1.\n'
            "\n"
            "GENERATED by tools/derive_sswu.py - the isogeny coefficients are\n"
            "DERIVED (division polynomial -> rational kernel -> Velu -> codomain\n"
            "scaling), not transcribed; the normalization is pinned by the IC\n"
            "known-answer vectors mirrored from the reference\n"
            "(utils/verify-bls-signatures/tests/tests.rs:96-127).  Maps are dense\n"
            "little-endian coefficient lists over Fp:\n"
            "  x' = X_NUM(x)/X_DEN(x)\n"
            "  y' = y * Y_NUM(x)/Y_DEN(x)\n"
            '"""\n\n'
            f"A_PRIME = {hex(A_PRIME)}\n\n"
            f"B_PRIME = {hex(B_PRIME)}\n\n"
            f"Z_SSWU = {Z_SSWU}\n\n"
            f"X_NUM = {fmt(xn)}\n\n"
            f"X_DEN = {fmt(xd)}\n\n"
            f"Y_NUM = {fmt(yn)}\n\n"
            f"Y_DEN = {fmt(yd)}\n"
        )
    print(f"wrote {os.path.normpath(out_path)}")


if __name__ == "__main__":
    main()
