"""Derive the RFC 9380 G1 SSWU isogeny for BLS12-381 from first principles.

The simplified-SWU map for BLS12-381 G1 targets an auxiliary curve
E': y^2 = x^3 + A'x + B' that is 11-isogenous to E: y^2 = x^3 + 4, followed
by an 11-isogeny E' -> E.  The RFC publishes E' and the isogeny as ~50 large
hex constants; this script *derives* them instead of trusting transcription:

  1. build the 11-division polynomial of E (degree 60) over Fp;
  2. factor out the two order-11 rational-subgroup kernel polynomials
     (degree 5) via x^(p^k) mod psi_11 power maps + Cantor-Zassenhaus;
  3. run Velu/Kohel's formulas (power sums + the P*h' mod h trick for
     sums over kernel roots) to get, for each kernel, the codomain curve
     E2 and the rational maps of the isogeny E -> E2;
  4. on E2, repeat 1-3 to find the dual direction E2 -> E3 ~ E and the
     scaling back to y^2 = x^3 + 4;
  5. enumerate the finitely many Fp-normalizations (sqrt/6th-root choices
     = Aut(E) and the two kernels) of the composite
       SSWU(A',B',Z=11) -> E' -> E2 -> E3 -> E
     and select the unique candidate that reproduces the IC known-answer
     signature vectors carried by the reference
     (/root/reference/utils/verify-bls-signatures/tests/tests.rs:96-127).

The only constant taken on faith is A' (checked, with everything else, by
the 128-bit-strength KAT); B' and all isogeny coefficients come out of the
algebra.  Results are emitted as cess_tpu/ops/_sswu_g1.py.

Run:  python tools/derive_sswu.py          (stage results cached in
      tools/_sswu_cache.json; a full cold run takes a few minutes)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cess_tpu.ops import bls12_381 as bls  # noqa: E402
from cess_tpu.ops.bls12_381 import P  # noqa: E402

CACHE = os.path.join(os.path.dirname(__file__), "_sswu_cache.json")

# RFC 9380 §8.8.1 ciphersuite parameters for BLS12381G1 (the one recalled
# input; everything downstream is derived and KAT-verified).
A_PRIME = int(
    "0x144698a3b8e9433d693a02c96d4982b0ea985383ee66a8d8e8981aef"
    "d881ac98936f8da0e0f97f5cf428082d584c1d",
    16,
)
Z_SSWU = 11

A_E, B_E = 0, 4  # E: y^2 = x^3 + 4


# ---------------------------------------------------------------- Fp polys
# Dense little-endian coefficient lists over Fp.


def pstrip(f):
    while f and f[-1] == 0:
        f.pop()
    return f


def padd(f, g):
    n = max(len(f), len(g))
    return pstrip([
        ((f[i] if i < len(f) else 0) + (g[i] if i < len(g) else 0)) % P
        for i in range(n)
    ])


def psub(f, g):
    n = max(len(f), len(g))
    return pstrip([
        ((f[i] if i < len(f) else 0) - (g[i] if i < len(g) else 0)) % P
        for i in range(n)
    ])


def pmul(f, g):
    if not f or not g:
        return []
    out = [0] * (len(f) + len(g) - 1)
    for i, a in enumerate(f):
        if a:
            for j, b in enumerate(g):
                out[i + j] = (out[i + j] + a * b) % P
    return pstrip(out)


def pscale(f, c):
    c %= P
    return pstrip([a * c % P for a in f])


def pdivmod(f, g):
    """Polynomial division with remainder (g nonzero)."""
    f = list(f)
    q = [0] * max(0, len(f) - len(g) + 1)
    ginv = pow(g[-1], P - 2, P)
    while len(f) >= len(g) and pstrip(f):
        if not f:
            break
        c = f[-1] * ginv % P
        d = len(f) - len(g)
        q[d] = c
        for i, b in enumerate(g):
            f[i + d] = (f[i + d] - c * b) % P
        pstrip(f)
    return pstrip(q), pstrip(f)


def pmod(f, g):
    return pdivmod(f, g)[1]


def pexactdiv(f, g):
    q, r = pdivmod(f, g)
    assert not r, "division expected to be exact"
    return q


def pgcd(f, g):
    while g:
        f, g = g, pmod(f, g)
    if f:
        f = pscale(f, pow(f[-1], P - 2, P))  # monic
    return f


def pderiv(f):
    return pstrip([i * f[i] % P for i in range(1, len(f))])


def ppowmod(base, exp, mod):
    result = [1]
    base = pmod(base, mod)
    while exp:
        if exp & 1:
            result = pmod(pmul(result, base), mod)
        base = pmod(pmul(base, base), mod)
        exp >>= 1
    return result


def pcompose_mod(f, g, mod):
    """f(g(x)) mod `mod` by Horner."""
    acc = []
    for c in reversed(f):
        acc = pmod(padd(pmul(acc, g), [c]), mod)
    return acc


def peval(f, x):
    acc = 0
    for c in reversed(f):
        acc = (acc * x + c) % P
    return acc


# ------------------------------------------------- curve ring Fp[x,y]/(E)
# Elements (f0, f1) = f0(x) + f1(x)*y with y^2 -> x^3 + a x + b.


def ring_mul(u, v, c):
    f0, f1 = u
    g0, g1 = v
    cross = pmul(pmul(f1, g1), c)
    return (padd(pmul(f0, g0), cross), padd(pmul(f0, g1), pmul(f1, g0)))


def division_polys(a, b, upto):
    """psi_0..psi_upto in the curve ring for y^2 = x^3 + a x + b."""
    c = [b % P, a % P, 0, 1]  # x^3 + a x + b
    psi = [None] * (upto + 1)
    psi[0] = ([], [])
    psi[1] = ([1], [])
    psi[2] = ([], [2])
    psi[3] = (
        pstrip([
            (-(a * a)) % P,
            12 * b % P,
            6 * a % P,
            0,
            3,
        ]),
        [],
    )
    psi[4] = (
        [],
        pscale(
            [
                (-8 * b * b - a**3) % P,
                (-4 * a * b) % P,
                (-5 * a * a) % P,
                20 * b % P,
                5 * a % P,
                0,
                1,
            ],
            4,
        ),
    )
    for m in range(5, upto + 1):
        k = m // 2
        if m & 1:  # psi_{2k+1} = psi_{k+2} psi_k^3 - psi_{k-1} psi_{k+1}^3
            t1 = ring_mul(
                psi[k + 2], ring_mul(psi[k], ring_mul(psi[k], psi[k], c), c), c
            )
            t2 = ring_mul(
                psi[k - 1],
                ring_mul(psi[k + 1], ring_mul(psi[k + 1], psi[k + 1], c), c),
                c,
            )
            psi[m] = (psub(t1[0], t2[0]), psub(t1[1], t2[1]))
        else:  # psi_{2k} = psi_k (psi_{k+2} psi_{k-1}^2 - psi_{k-2} psi_{k+1}^2)/2y
            t1 = ring_mul(psi[k + 2], ring_mul(psi[k - 1], psi[k - 1], c), c)
            t2 = ring_mul(psi[k - 2], ring_mul(psi[k + 1], psi[k + 1], c), c)
            num = ring_mul(psi[k], (psub(t1[0], t2[0]), psub(t1[1], t2[1])), c)
            g, g1 = num
            assert not g1, "even psi numerator should be y-free"
            half = pow(2, P - 2, P)
            psi[m] = ([], pexactdiv(pscale(g, half), c))
    return psi


def psi11_poly(a, b):
    """The 11-division polynomial as a plain x-polynomial (degree 60)."""
    psi = division_polys(a, b, 13)
    f, f1 = psi[11]
    assert not f1
    assert len(f) - 1 == 60, f"psi11 degree {len(f) - 1}"
    return pscale(f, pow(f[-1], P - 2, P)), psi  # monic


# ------------------------------------------------- kernel extraction


def kernel_polys(a, b, cache_key, cache):
    """ALL monic degree-5 kernel polynomials of the Fp-rational order-11
    subgroups of y^2 = x^3 + a x + b.  For BLS12-381's E, Frobenius is a
    scalar mod 11, so every one of the 12 subgroups is rational and psi11
    splits into 12 quintic kernel polynomials."""
    if cache_key in cache:
        return [[int(v, 16) for v in k] for k in cache[cache_key]]
    f, _psi = psi11_poly(a, b)
    print(f"[{cache_key}] psi11 ready (deg {len(f) - 1}); computing x^p ...")
    xp_key = cache_key + "_xp"
    if xp_key in cache:
        xp = [int(v, 16) for v in cache[xp_key]]
    else:
        xp = ppowmod([0, 1], P, f)  # the slow step
        cache[xp_key] = [hex(v) for v in xp]
        save_cache(cache)
    print(f"[{cache_key}] x^p done; verifying x^(p^5) = x ...")
    xpk = xp
    for _ in range(4):
        xpk = pcompose_mod(xpk, xp, f)
    h = pgcd(psub(xpk, [0, 1]), f)
    assert len(h) - 1 == 60, (
        f"expected all psi11 roots in F_p^5, gcd degree {len(h) - 1}"
    )
    h1 = pgcd(psub(xp, [0, 1]), f)
    assert len(h1) <= 1, "unexpected rational 11-torsion x-coords"

    # Equal-degree factorization into irreducible quintics via the trace
    # map: T(r) = sum_k r^(p^k) is a constant c_i mod each quintic factor;
    # gcd(T^((p-1)/2) - 1, g) splits factors by the QR-ness of c_i.
    import random as _random

    rnd = _random.Random(0xCE55)

    def frob_powers(g):
        xg = pmod(xp, g)
        pows = [[0, 1], xg]
        for _ in range(3):
            pows.append(pcompose_mod(pows[-1], xg, g))
        return pows

    def split(g):
        if len(g) - 1 == 5:
            return [g]
        pows = frob_powers(g)
        while True:
            r = [rnd.randrange(P) for _ in range(len(g) - 1)]
            t = []
            for pw in pows:
                t = padd(t, pcompose_mod(r, pw, g))
            s = ppowmod(t, (P - 1) // 2, g)
            d = pgcd(psub(s, [1]), g)
            if 0 < len(d) - 1 < len(g) - 1:
                rest = pexactdiv(g, d)
                rest = pscale(rest, pow(rest[-1], P - 2, P))
                print(
                    f"[{cache_key}] split {len(g)-1} -> "
                    f"{len(d)-1} + {len(rest)-1}"
                )
                return split(d) + split(rest)

    kernels = split(f)
    assert len(kernels) == 12 and all(len(k) - 1 == 5 for k in kernels)
    cache[cache_key] = [[hex(v) for v in k] for k in kernels]
    save_cache(cache)
    return kernels


def dual_kernel_poly(ker, other, maps):
    """Kernel polynomial of the dual isogeny, computed in F_{p^5}.

    ker phi-hat = phi(E[11]); the x-coords of the image of any OTHER
    order-11 subgroup generate it.  Work in F_{p^5} = Fp[x]/other(x): the
    image x-coordinate phi_x(alpha) and its five Frobenius conjugates give
    the minimal polynomial directly."""
    Nx, Dx, _Ny, _Dy = maps
    k = other  # irreducible quintic

    def fmul(u, v):
        return pmod(pmul(u, v), k)

    def finv(u):
        # extended Euclid in Fp[x]/k
        r0, r1 = list(k), pmod(u, k)
        s0, s1 = [], [1]
        while r1:
            q, r2 = pdivmod(r0, r1)
            r0, r1 = r1, r2
            s0, s1 = s1, psub(s0, pmul(q, s1))
        c = pow(r0[0], P - 2, P)  # r0 is a nonzero constant
        return pscale(pmod(s0, k), c)

    def feval_poly(f):
        # evaluate f (coeffs in Fp) at alpha: just reduce f mod k
        return pmod(f, k)

    alpha_img = fmul(feval_poly(Nx), finv(feval_poly(Dx)))
    xp_k = ppowmod([0, 1], P, k)
    conjs = [alpha_img]
    for _ in range(4):
        conjs.append(pcompose_mod(conjs[-1], xp_k, k))
    # minpoly(X) = prod (X - conj_j), coefficients in F_{p^5}; they must
    # collapse to Fp constants.
    coeffs = [[1]]
    for c in conjs:
        # multiply (X - c) into coeffs
        new = [[] for _ in range(len(coeffs) + 1)]
        for i, co in enumerate(coeffs):
            new[i + 1] = padd(new[i + 1], co)
            new[i] = psub(new[i], fmul(co, c))
        coeffs = new
    out = []
    for co in coeffs:
        assert len(co) <= 1, "dual kernel coefficient not in Fp"
        out.append(co[0] if co else 0)
    assert len(out) == 6 and out[5] == 1
    return out


# ------------------------------------------------- Velu / Kohel


def velu_from_kernel(a, b, h):
    """11-isogeny with kernel polynomial h (monic, degree 5) from
    y^2 = x^3 + a x + b.  Returns (a2, b2, Nx, Dx, Ny, Dy) where
    phi(x, y) = (Nx(x)/Dx(x), y * Ny(x)/Dy(x)).

    Velu sums over kernel roots are evaluated without leaving Fp via
      sum_i Q(x_i)/(x - x_i) = (Q * h' mod h)(x) / h(x)      (deg Q < 5)
    and power sums from Newton's identities.
    """
    d = len(h) - 1
    assert d == 5
    # Newton power sums p1..p3 from monic coefficients.
    e1 = (-h[d - 1]) % P
    e2 = h[d - 2] % P
    e3 = (-h[d - 3]) % P
    p1 = e1
    p2 = (e1 * p1 - 2 * e2) % P
    p3 = (e1 * p2 - e2 * p1 + 3 * e3) % P

    # t_i = 6 x_i^2 + 2a ; u_i = 4(x_i^3 + a x_i + b)
    # W = sum(u_i + x_i t_i) = sum(10 x^3 + 6a x + 4b)
    T = (6 * p2 + 2 * a * d) % P
    W = (10 * p3 + 6 * a * p1 + 4 * b * d) % P
    a2 = (a - 5 * T) % P
    b2 = (b - 7 * W) % P

    hp = pderiv(h)
    # T1(x) = (t(x) * h'(x)) mod h ;  U1(x) = (u(x) * h'(x)) mod h
    tpoly = pstrip([2 * a % P, 0, 6])
    upoly = pstrip([4 * b % P, 4 * a % P, 0, 4])
    T1 = pmod(pmul(tpoly, hp), h)
    U1 = pmod(pmul(upoly, hp), h)
    # phi_x = x + T1/h + (U1 h' - U1' h)/h^2  =  Nx / h^2
    h2 = pmul(h, h)
    Nx = padd(
        pmul([0, 1], h2),
        padd(pmul(T1, h), psub(pmul(U1, hp), pmul(pderiv(U1), h))),
    )
    Dx = h2
    # phi_y = y * d/dx(phi_x) = y * (Nx' h - 2 Nx h') / h^3
    Ny = psub(pmul(pderiv(Nx), h), pscale(pmul(Nx, hp), 2))
    Dy = pmul(h2, h)
    assert len(Nx) - 1 == 11 and len(Dx) - 1 == 10
    return a2, b2, Nx, Dx, Ny, Dy


def on_curve(a, b, x, y):
    return (y * y - (x * x % P * x + a * x + b)) % P == 0


def random_point(a, b, seed=5):
    x = seed
    while True:
        rhs = (x * x % P * x + a * x + b) % P
        y = pow(rhs, (P + 1) // 4, P)
        if y * y % P == rhs:
            return x, y
        x += 1


def apply_map(maps, x, y):
    Nx, Dx, Ny, Dy = maps
    den = peval(Dx, x)
    if den == 0:
        return None  # kernel point -> infinity
    X = peval(Nx, x) * pow(den, P - 2, P) % P
    Y = y * peval(Ny, x) % P * pow(peval(Dy, x), P - 2, P) % P
    return X, Y


# ------------------------------------------------- roots in Fp


def sqrt_fp(v):
    r = pow(v, (P + 1) // 4, P)
    return r if r * r % P == v % P else None


def nth_roots(v, n):
    """All n-th roots of v in Fp for small n (via factorization of the
    multiplicative order structure; implemented for n | 6)."""
    v %= P
    assert n in (2, 3, 6)
    if n == 2:
        r = sqrt_fp(v)
        return [] if r is None else sorted({r, P - r})
    if n == 3:
        if pow(v, (P - 1) // 3, P) != 1:
            return []
        from sympy.ntheory.residue_ntheory import nthroot_mod

        roots = nthroot_mod(v, 3, P, all_roots=True)
        assert roots and all(pow(r, 3, P) == v for r in roots)
        return sorted(int(r) for r in roots)
    roots = []
    for c in nth_roots(v, 3):
        roots.extend(nth_roots(c, 2))
    return sorted(set(roots))


# ------------------------------------------------- cache


def load_cache():
    if os.path.exists(CACHE):
        with open(CACHE) as fh:
            return json.load(fh)
    return {}


def save_cache(cache):
    with open(CACHE, "w") as fh:
        json.dump(cache, fh)


# ------------------------------------------------- KAT


def kat_ok(map_fn):
    """True iff hash-with-candidate-map reproduces the reference IC vector
    (reference: utils/verify-bls-signatures/tests/tests.rs:121-127)."""
    sk = int("6f3977f6051e184b2c412daa1b5c0115ef7ab347cac8d808ffa2c26bd0658243", 16)
    msg = bytes.fromhex(
        "50484522ad8aede64ec7f86b9273b7ed3940481acf93cdd40a2b77f2be2734a1"
        "4012b2492b6363b12adaeaf055c573e4611b085d2e0fe2153d72453a95eaebf3"
        "50ac3ba6a26ba0bc79f4c0bf5664dfdf5865f69f7fc6b58ba7d068e8"
    )
    expected = "8f7ad830632657f7b3eae17fd4c3d9ff5c13365eea8d33fd0a1a6d8fbebc5152e066bb0ad61ab64e8a8541c8e3f96de9"
    u0, u1 = bls.hash_to_field_fp(msg, bls.DST_G1, 2)
    q0 = map_fn(u0)
    q1 = map_fn(u1)
    if q0 is None or q1 is None:
        return False
    h = bls.clear_cofactor_g1(q0 + q1)
    sig = h.mul(sk).to_bytes().hex()
    return sig == expected


def sswu_raw(u, A, B, Z):
    """RFC 9380 §6.6.2 simplified SWU for AB != 0 curves; returns a point
    on y^2 = x^3 + A x + B."""
    u %= P
    tv1 = (Z * Z % P * pow(u, 4, P) + Z * u * u) % P
    if tv1 == 0:
        x1 = B * pow((Z * A) % P, P - 2, P) % P
    else:
        x1 = (-B) % P * pow(A, P - 2, P) % P * (1 + pow(tv1, P - 2, P)) % P
    gx1 = (pow(x1, 3, P) + A * x1 + B) % P
    y1 = sqrt_fp(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = Z * u * u % P * x1 % P
        gx2 = (pow(x2, 3, P) + A * x2 + B) % P
        y2 = sqrt_fp(gx2)
        assert y2 is not None, "SSWU: neither branch square (impossible)"
        x, y = x2, y2
    if (u % 2) != (y % 2):  # sgn0 alignment
        y = P - y
    return x, y


# ------------------------------------------------- main derivation


def main():
    cache = load_cache()

    print("== stage 1: the 12 kernels of E ==")
    kernels_E = kernel_polys(A_E, B_E, "kernels_E", cache)

    candidates = []
    for ki, hker in enumerate(kernels_E):
        a2, b2, *maps_E_E2 = velu_from_kernel(A_E, B_E, hker)
        # sanity: isogeny maps E points onto E2
        x0, y0 = random_point(A_E, B_E)
        img = apply_map(maps_E_E2, x0, y0)
        assert img and on_curve(a2, b2, *img), "Velu map sanity failed"

        # Does E' (A', B'?) live over this codomain? need u^4 = a2/A'.
        ratio = a2 * pow(A_PRIME, P - 2, P) % P
        u2s = [u2 for u2 in nth_roots(ratio, 2) if nth_roots(u2, 2)]
        if not u2s:
            continue
        print(f"kernel {ki}: codomain admits E' model (u2 count {len(u2s)})")

        # dual isogeny kernel on E2: image of any other subgroup.
        other = kernels_E[(ki + 1) % len(kernels_E)]
        hdual = dual_kernel_poly(hker, other, maps_E_E2)
        a3, b3, *maps_E2_E3 = velu_from_kernel(a2, b2, hdual)
        assert a3 == 0, f"dual codomain not j=0 (a3={hex(a3)[:16]}..)"
        x1, y1 = random_point(a2, b2)
        img2 = apply_map(maps_E2_E3, x1, y1)
        assert img2 and on_curve(a3, b3, *img2)

        for u2 in u2s:
            u = nth_roots(u2, 2)[0]
            B_candidate = b2 * pow(pow(u2, 3, P), P - 2, P) % P
            for v in nth_roots(4 * pow(b3, P - 2, P) % P, 6):
                candidates.append(
                    (ki, u, u2, B_candidate, maps_E_E2, a2, b2,
                     maps_E2_E3, b3, v)
                )

    print(f"== stage 2: {len(candidates)} composite candidates; KAT-testing ==")
    from cess_tpu.ops.bls12_381 import G1Point

    for cand in candidates:
        (ki, u, u2, Bc, mE, a2, b2, mD, b3, v) = cand

        def compose(ufield, _c=cand):
            (ki, u, u2, Bc, mE, a2, b2, mD, b3, v) = _c
            x, y = sswu_raw(ufield, A_PRIME, Bc, Z_SSWU)
            # sigma: E' -> E2
            x, y = u2 * x % P, u2 * u % P * y % P
            assert on_curve(a2, b2, x, y)
            # dual isogeny E2 -> E3
            res = apply_map(mD, x, y)
            if res is None:
                return None
            x, y = res
            x, y = v * v % P * x % P, pow(v, 3, P) * y % P
            if not on_curve(A_E, B_E, x, y):
                return None
            return G1Point(x, y)

        if kat_ok(compose):
            print(f"KAT PASS: kernel {ki} u2={hex(u2)[:18]}.. v={hex(v)[:18]}..")
            emit(cand)
            return
    print("NO candidate passed the KAT — check A' or assumptions.")
    sys.exit(1)


def emit(cand):
    """Flatten the winning composite into x_num/x_den/y_num/y_den
    coefficient lists (the RFC iso_map shape) and write the generated
    module."""
    (ki, u, u2, Bc, mE, a2, b2, mD, b3, v) = cand
    Nx, Dx, Ny, Dy = mD

    # pre-scale: x -> u2 * x on inputs of the dual maps
    def prescale(f, s):
        return [c * pow(s, i, P) % P for i, c in enumerate(f)]

    Nxs = prescale(Nx, u2)
    Dxs = prescale(Dx, u2)
    Nys = prescale(Ny, u2)
    Dys = prescale(Dy, u2)
    # post-scale x by v^2, y by v^3 * (u2 * u) [the sigma y factor]
    xnum = pscale(Nxs, v * v % P)
    xden = Dxs
    ynum = pscale(Nys, pow(v, 3, P) * (u2 * u % P) % P)
    yden = Dys
    # normalize: make x_den monic (divide num&den pairs by leading coeff)
    c = pow(xden[-1], P - 2, P)
    xnum, xden = pscale(xnum, c), pscale(xden, c)
    c = pow(yden[-1], P - 2, P)
    ynum, yden = pscale(ynum, c), pscale(yden, c)

    out = os.path.join(
        os.path.dirname(__file__), "..", "cess_tpu", "ops", "_sswu_g1.py"
    )
    with open(out, "w") as fh:
        fh.write(
            '"""GENERATED by tools/derive_sswu.py — do not edit.\n\n'
            "RFC 9380 G1 simplified-SWU auxiliary curve and 11-isogeny for\n"
            "BLS12-381, derived via division polynomials + Velu's formulas\n"
            "and pinned by the IC signature KAT carried by the reference\n"
            "(utils/verify-bls-signatures/tests/tests.rs).  The values\n"
            "coincide with RFC 9380 Appendix E.2 by construction.\n"
            '"""\n\n'
        )
        fh.write(f"SSWU_A = {hex(A_PRIME)}\n")
        fh.write(f"SSWU_B = {hex(Bc)}\n")
        fh.write(f"SSWU_Z = {Z_SSWU}\n\n")
        for name, coeffs in (
            ("ISO_X_NUM", xnum),
            ("ISO_X_DEN", xden),
            ("ISO_Y_NUM", ynum),
            ("ISO_Y_DEN", yden),
        ):
            fh.write(f"{name} = [\n")
            for cco in coeffs:
                fh.write(f"    {hex(cco)},\n")
            fh.write("]\n\n")
    print(f"wrote {os.path.normpath(out)}")


if __name__ == "__main__":
    main()
