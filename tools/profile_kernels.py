"""Pure device-compute timings for the verify kernels (resident inputs)."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(label, fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)  # compile
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    print(f"  {label:44s} {best * 1000:9.1f} ms", file=sys.stderr, flush=True)
    return best


def main():
    from cess_tpu.ops import g1, h2c

    rng = np.random.default_rng(7)

    # ---- transfers with random data
    for mb in (1, 4, 16):
        h = rng.integers(0, 1 << 30, size=(mb * 256 * 1024,), dtype=np.int32)
        d = jax.device_put(h); jax.block_until_ready(d)
        t0 = time.perf_counter(); d = jax.device_put(h); jax.block_until_ready(d)
        print(f"  h2d random int32 {mb}MB: {(time.perf_counter()-t0)*1e3:.1f} ms",
              file=sys.stderr)
        t0 = time.perf_counter(); _ = np.asarray(d)
        print(f"  d2h random int32 {mb}MB: {(time.perf_counter()-t0)*1e3:.1f} ms",
              file=sys.stderr)

    N = int(os.environ.get("PROF_LANES", "65536"))
    print(f"N={N} lanes", file=sys.stderr)

    # ---- SSWU map kernel, device-resident inputs
    u = jnp.asarray(rng.integers(0, 4096, size=(33, 2, N), dtype=np.int32))
    sgn = jnp.asarray(rng.integers(0, 2, size=(2, N), dtype=np.int32))
    exc = jnp.zeros((2, N), jnp.int32)
    dt = timeit("SSWU map kernel", lambda: h2c._map_pairs_kernel(u, sgn, exc))
    print(f"    -> {dt / N * 1e6:.2f} us/pair; per proof(47): {dt / N * 47 * 1e3:.3f} ms",
          file=sys.stderr)

    X = jnp.asarray(rng.integers(0, 4096, size=(33, N), dtype=np.int32))
    Y = jnp.asarray(rng.integers(0, 4096, size=(33, N), dtype=np.int32))
    Z = jnp.asarray(rng.integers(0, 4096, size=(33, N), dtype=np.int32))

    # ---- grouped ladder MSM at various bit widths
    for bits in (224, 160, 128):
        s = jnp.asarray(
            rng.integers(0, 4096, size=(g1.R_LIMBS, N), dtype=np.int32))
        dt = timeit(f"grouped ladder MSM bits={bits} g=64",
                    lambda s=s, bits=bits: g1._msm_kernel(
                        X, Y, Z, s, bits=bits, group=64))
        print(f"    -> per proof(64 lanes): {dt / (N // 64) * 1e3:.3f} ms",
              file=sys.stderr)

    # ---- flat Pippenger at 352 and 160 bits
    for bits in ():
        nw = -(-bits // 12)
        d = jnp.asarray(rng.integers(0, 4096, size=(nw, N), dtype=np.int32))
        dt = timeit(f"flat Pippenger bits={bits} ({nw} win)",
                    lambda d=d, bits=bits: g1.msm_flat_device((X, Y, Z), np.asarray(d), bits))
        print(f"    -> per proof(47 lanes): {dt / (N / 47) * 1e3:.3f} ms",
              file=sys.stderr)

    # ---- small-lane ladder (sigma/u side shapes)
    for lanes, bits in ((1024, 128), (256, 255)):
        Xs, Ys, Zs = X[:, :lanes], Y[:, :lanes], Z[:, :lanes]
        s = jnp.asarray(
            rng.integers(0, 4096, size=(g1.R_LIMBS, lanes), dtype=np.int32))
        timeit(f"flat ladder MSM lanes={lanes} bits={bits}",
               lambda Xs=Xs, Ys=Ys, Zs=Zs, s=s, bits=bits: g1._msm_kernel(
                   Xs, Ys, Zs, s, bits=bits))


if __name__ == "__main__":
    main()
