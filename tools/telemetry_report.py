"""Fleet telemetry reporter: scrape N nodes, emit one throughput report.

The metrics-backed throughput report ROADMAP item 5 requires: polls
`system_health` / `sync_status` over a window for rate and lag series,
then scrapes `system_metrics` (Prometheus text, parsed by
node/metrics.parse_exposition) and `system_traces` once at the end,
and renders a single JSON + markdown artifact:

  * blocks/s and extrinsics/s over the window (fleet-level),
  * finality lag p50/p95 (per node, sampled — the observable the
    GRANDPA accountable-safety drills presume),
  * block import stage histograms (sig batch / re-execution /
    snapshot) per node,
  * gossip drop totals per node (partition visibility),
  * per-proof verify ms + per-stage breakdown from the proof data
    plane's always-on histograms (proof/xla_backend.py), merged from
    the nodes and any local in-process registries (the soak's TEE
    verification runs in the test process),
  * stitched-trace inventory (how many block traces span >1 node).

Used two ways: as a CLI —

    python tools/telemetry_report.py --nodes 127.0.0.1:9944,... \
        --duration 30 --out-json report.json --out-md report.md

— and as a library by the chaos soak (tests/test_zz_chaos_testnet.py),
which samples through its existing wait loops and commits the report
artifact at the end of every soak.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # repo-root invocation

from cess_tpu.node import metrics as m  # noqa: E402
from cess_tpu.node.rpc import RpcError, rpc_call  # noqa: E402


def percentile(series: list[float], q: float) -> float:
    """Nearest-rank percentile over a sample series (0 when empty)."""
    if not series:
        return 0.0
    ordered = sorted(series)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


def histogram_summary(fam: m.MetricFamily) -> dict:
    """{count, mean_ms, p50_ms, p95_ms} estimated from exposition
    buckets (upper-bound attribution, the standard Prometheus
    histogram_quantile shape)."""
    h = fam.histogram()
    count = h["count"]
    if not count:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p95_ms": 0.0}

    finite = [le for le, _ in h["buckets"] if le != float("inf")]
    top = finite[-1] * 1000.0 if finite else 0.0

    def est(q: float) -> float:
        rank = q * count
        for le, cumulative in h["buckets"]:
            if cumulative >= rank:
                # rank in the +Inf bucket clamps to the largest finite
                # bound (the prometheus histogram_quantile convention)
                # — NOT zero, which would under-report exactly when
                # latencies are worst
                return top if le == float("inf") else le * 1000.0
        return top

    return {
        "count": int(count),
        "mean_ms": round(h["sum"] / count * 1000.0, 3),
        "p50_ms": est(0.50),
        "p95_ms": est(0.95),
    }


class FleetCollector:
    """Samples a fleet over a window, then builds the report."""

    IMPORT_STAGES = ("sig_batch", "execute", "snapshot")
    PROOF_STAGES = ("host_prep", "u_fold", "sigma_fold",
                    "chunk_program", "dispatch_wait", "pairing")

    def __init__(self, nodes: list[tuple[str, int]], timeout: float = 5.0):
        self.nodes = list(nodes)
        self.timeout = timeout
        self.t_start = time.time()
        self.samples: dict[str, list[dict]] = {
            self._label(n): [] for n in self.nodes
        }
        # extrinsic counters are cumulative from node start: snapshot
        # them at collector construction so the report's extrinsics/s
        # is a WINDOW delta, not lifetime-total / window
        self._ext_base: dict[str, float] = {}
        for node in self.nodes:
            try:
                fams = m.parse_exposition(
                    self._call(node, "system_metrics"))
                self._ext_base[self._label(node)] = fams.get(
                    "cess_extrinsics_applied", m.MetricFamily("")
                ).value()
            except (OSError, RpcError, ValueError):
                pass

    @staticmethod
    def _label(node: tuple[str, int]) -> str:
        return f"{node[0]}:{node[1]}"

    def _call(self, node, method, params=None):
        return rpc_call(node[0], node[1], method, params or [],
                        timeout=self.timeout)

    def sample(self) -> None:
        """One cheap poll per node: health + head/finality numbers.
        Unreachable nodes are skipped (mid-restart under chaos)."""
        now = time.time()
        for node in self.nodes:
            try:
                health = self._call(node, "system_health")
            except (OSError, RpcError, ValueError):
                continue
            self.samples[self._label(node)].append(
                {"t": now, "health": health}
            )

    # ------------------------------------------------------ report

    def _scrape_full(self, node) -> dict:
        out: dict = {}
        for key, method in (("metrics", "system_metrics"),
                            ("traces", "system_traces")):
            try:
                out[key] = self._call(node, method)
            except (OSError, RpcError, ValueError):
                out[key] = None
        if out.get("metrics"):
            # a node killed mid-response hands back truncated
            # exposition text; a parse blow-up here must cost this
            # node its scrape, never the whole fleet report
            try:
                out["families"] = m.parse_exposition(out["metrics"])
            except Exception:
                out["metrics"] = None
        # node down at report time (crashed mid-window and not yet —
        # or never — restarted): flag it so report() can mark the
        # entry instead of silently rendering zeros
        out["unreachable"] = (out.get("metrics") is None
                              and out.get("traces") is None)
        return out

    def report(self, extra_registries: tuple = (),
               elapsed_s: float | None = None) -> dict:
        """Build the report dict.  `extra_registries` are in-process
        metrics registries (node/metrics.Registry) merged in as the
        pseudo-node "local" — the soak's proof verification runs in
        the test process, so its per-proof histograms live there."""
        elapsed = elapsed_s or max(1e-9, time.time() - self.t_start)
        per_node: dict[str, dict] = {}
        lag_all: list[float] = []
        first_best: list[float] = []
        last_best: list[float] = []
        ext_rate_total = 0.0
        scrapes = {
            self._label(node): self._scrape_full(node)
            for node in self.nodes
        }

        for node in self.nodes:
            label = self._label(node)
            series = self.samples[label]
            lags = [s["health"].get("finalityLag", 0) for s in series]
            bests = [s["health"].get("bestBlock", 0) for s in series]
            lag_all.extend(lags)
            if bests:
                first_best.append(bests[0])
                last_best.append(bests[-1])
            scrape = scrapes[label]
            fams = scrape.get("families") or {}
            entry: dict = {
                "unreachable": bool(scrape.get("unreachable")),
                "samples": len(series),
                "bestBlock": bests[-1] if bests else None,
                "finalityLag": {
                    "last": lags[-1] if lags else None,
                    "p50": percentile(lags, 0.50),
                    "p95": percentile(lags, 0.95),
                },
                "gossipDropped": (
                    series[-1]["health"].get("gossipDropped", {})
                    if series else {}
                ),
                "peersSeen": (
                    series[-1]["health"].get("peersSeen", {})
                    if series else {}
                ),
            }
            if fams:
                entry["blocksProduced"] = fams.get(
                    "cess_blocks_produced", m.MetricFamily("")).value()
                entry["blocksImported"] = fams.get(
                    "cess_blocks_imported", m.MetricFamily("")).value()
                entry["extrinsicsApplied"] = fams.get(
                    "cess_extrinsics_applied", m.MetricFamily("")).value()
                # clamp at zero: a crash-restarted node's counter
                # resets below its construction-time baseline (its
                # post-restart work is undercounted rather than
                # driving the fleet rate negative)
                ext_rate_total += max(
                    0.0,
                    entry["extrinsicsApplied"]
                    - self._ext_base.get(label, 0.0),
                )
                entry["importStages"] = {
                    stage: histogram_summary(fams[name])
                    for stage in self.IMPORT_STAGES
                    if (name := f"cess_import_{stage}_seconds") in fams
                }
                # tx-pool families (fee market, node/service.py): the
                # rejection counter is labelled by reason — keep both
                # the per-reason breakdown and the total
                rej = fams.get("cess_pool_rejections", m.MetricFamily(
                    "cess_pool_rejections"))
                entry["pool"] = {
                    "size": fams.get(
                        "cess_pool_size", m.MetricFamily("")).value(),
                    "bytes": fams.get(
                        "cess_pool_bytes", m.MetricFamily("")).value(),
                    "evictions": fams.get(
                        "cess_pool_evictions", m.MetricFamily("")).value(),
                    "rejections": rej.total(),
                    "rejectionsByReason": {
                        labels.get("reason", "?"): v
                        for sname, labels, v in rej.samples
                        if sname == rej.name
                    },
                    "feeTotal": fams.get(
                        "cess_pool_fee_total", m.MetricFamily("")).value(),
                }
                # read-plane families (light/replica.py): present only
                # on read replicas — reads served, proof build latency,
                # and the justification-batch amortisation (verified
                # per weighted pairing; >1 means batching is paying)
                if "cess_replica_reads_total" in fams:
                    verified = fams.get(
                        "cess_light_justifications_verified",
                        m.MetricFamily("")).value()
                    pairings = fams.get(
                        "cess_light_batch_pairings",
                        m.MetricFamily("")).value()
                    entry["readPlane"] = {
                        "reads": fams["cess_replica_reads_total"].value(),
                        "proofLatency": (
                            histogram_summary(
                                fams["cess_replica_proof_seconds"])
                            if "cess_replica_proof_seconds" in fams
                            else None),
                        "justificationsVerified": verified,
                        "batchPairings": pairings,
                        "justsPerPairing": round(
                            verified / pairings, 2) if pairings else 0.0,
                    }
            per_node[label] = entry

        # fleet rates: the chain advances as one, so blocks/s is the
        # best head's progress over the window, not a per-node sum
        blocks_delta = (
            max(last_best) - max(first_best)
            if first_best and last_best else 0.0
        )

        # stitched traces: block traces whose spans live on >1 node.
        # Defensive .get()s: a trace summary from a node that died
        # mid-serialisation may be missing keys — drop the record,
        # keep the report.
        trace_nodes: dict[str, set] = {}
        for label, scrape in scrapes.items():
            summary = scrape.get("traces") or {}
            traces = summary.get("traces", []) if isinstance(
                summary, dict) else []
            for t in traces:
                if not isinstance(t, dict):
                    continue
                # import.batch: the pipelined gossip drain wraps a
                # block's import spans, so on importers the block's
                # trace roots at the batch span, not block.import
                if t.get("root") in ("block.author", "block.import",
                                     "import.batch") \
                        and t.get("traceId"):
                    trace_nodes.setdefault(t["traceId"], set()).add(label)
        stitched = sum(1 for nodes in trace_nodes.values()
                       if len(nodes) > 1)

        # proof data plane: merge node expositions + local registries.
        # The proof-stage registry is PROCESS-wide (every node in one
        # process serves the same one via system_metrics, and a caller
        # may pass it again through extra_registries), so sources are
        # deduped by their proof-family fingerprint before summing —
        # otherwise co-hosted nodes multi-count the same checks.
        proof: dict = {}
        proof_sources = []
        seen_fp = set()
        for fams in (
            [scrape.get("families") or {} for scrape in scrapes.values()]
            + [m.parse_exposition(reg.render())
               for reg in extra_registries]
        ):
            fp = tuple(
                (name, round(fams[name].value(), 9))
                for name in ("cess_proofs_verified",
                             "cess_proof_checks",
                             "cess_proof_verify_seconds_total")
                if name in fams
            )
            if fp and fp in seen_fp:
                continue
            seen_fp.add(fp)
            proof_sources.append(fams)
        total_proofs = sum(
            f.get("cess_proofs_verified", m.MetricFamily("")).value()
            for f in proof_sources
        )
        total_seconds = sum(
            f.get("cess_proof_verify_seconds_total",
                  m.MetricFamily("")).value()
            for f in proof_sources
        )
        if total_proofs:
            proof["proofs"] = int(total_proofs)
            proof["per_proof_ms"] = round(
                total_seconds / total_proofs * 1000.0, 3)
            proof["stages"] = {}
            for stage in self.PROOF_STAGES:
                name = f"cess_proof_stage_{stage}_seconds"
                fams_with = [f[name] for f in proof_sources if name in f]
                if not fams_with:
                    continue
                count = sum(f.histogram()["count"] for f in fams_with)
                total = sum(f.histogram()["sum"] for f in fams_with)
                proof["stages"][stage] = {
                    "count": int(count),
                    "total_s": round(total, 4),
                    "mean_ms": round(
                        total / count * 1000.0, 3) if count else 0.0,
                }

        # fee-market pressure: how much intake the pools turned away
        # vs how much work the chain actually applied — the spam-drop
        # rate a flood soak watches alongside paid-traffic inclusion
        rejections_total = sum(
            e.get("pool", {}).get("rejections", 0.0)
            for e in per_node.values()
        )
        applied_total = sum(
            e.get("extrinsicsApplied", 0.0) for e in per_node.values()
        )
        return {
            "generated_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "window_s": round(elapsed, 2),
            "nodes": len(self.nodes),
            "unreachable_nodes": sum(
                1 for e in per_node.values() if e.get("unreachable")),
            "fleet": {
                "blocks_per_s": round(blocks_delta / elapsed, 4),
                "extrinsics_per_s": round(ext_rate_total / elapsed, 4),
                "finality_lag_p50": percentile(lag_all, 0.50),
                "finality_lag_p95": percentile(lag_all, 0.95),
                "stitched_traces": stitched,
                "gossip_drops_total": sum(
                    sum(e["gossipDropped"].values())
                    for e in per_node.values()
                ),
                "pool_rejections_total": rejections_total,
                "pool_evictions_total": sum(
                    e.get("pool", {}).get("evictions", 0.0)
                    for e in per_node.values()
                ),
                "spam_drop_rate": round(
                    rejections_total
                    / max(1.0, rejections_total + applied_total), 4),
                "replica_reads_total": sum(
                    e.get("readPlane", {}).get("reads", 0.0)
                    for e in per_node.values()
                ),
                "replicas": sum(
                    1 for e in per_node.values() if "readPlane" in e
                ),
            },
            "per_node": per_node,
            "proof": proof,
        }


def to_markdown(report: dict) -> str:
    """Human-readable rendering of a report dict."""
    fleet = report["fleet"]
    lines = [
        "# Fleet telemetry report",
        "",
        f"Generated {report['generated_at']} over a "
        f"{report['window_s']} s window across {report['nodes']} nodes"
        + (f" ({report['unreachable_nodes']} unreachable at scrape "
           "time; fleet totals cover survivors only)"
           if report.get("unreachable_nodes") else "")
        + ".",
        "",
        "## Throughput",
        "",
        "| metric | value |",
        "|---|---|",
        f"| blocks/s | {fleet['blocks_per_s']} |",
        f"| extrinsics/s | {fleet['extrinsics_per_s']} |",
        f"| finality lag p50 (blocks) | {fleet['finality_lag_p50']} |",
        f"| finality lag p95 (blocks) | {fleet['finality_lag_p95']} |",
        f"| gossip drops (total) | {fleet['gossip_drops_total']} |",
        f"| cross-node stitched traces | {fleet['stitched_traces']} |",
        "",
        "## Tx pool",
        "",
        "| metric | value |",
        "|---|---|",
        f"| intake rejections (total) "
        f"| {fleet.get('pool_rejections_total', 0)} |",
        f"| evictions (total) | {fleet.get('pool_evictions_total', 0)} |",
        f"| spam drop rate | {fleet.get('spam_drop_rate', 0)} |",
        "",
        "## Per node",
        "",
    ]
    for label, entry in report["per_node"].items():
        lines += [
            f"### {label}"
            + (" — UNREACHABLE" if entry.get("unreachable") else ""),
            "",
            f"- best block {entry.get('bestBlock')}, finality lag "
            f"p50/p95 {entry['finalityLag']['p50']}/"
            f"{entry['finalityLag']['p95']} "
            f"({entry['samples']} samples)",
            f"- produced {entry.get('blocksProduced', 0)}, imported "
            f"{entry.get('blocksImported', 0)}, extrinsics applied "
            f"{entry.get('extrinsicsApplied', 0)}",
        ]
        drops = entry.get("gossipDropped") or {}
        if drops:
            lines.append(f"- gossip drops: {json.dumps(drops)}")
        pool = entry.get("pool") or {}
        if pool:
            lines.append(
                f"- pool: {int(pool['size'])} txs / "
                f"{int(pool['bytes'])} B, "
                f"{int(pool['evictions'])} evictions, "
                f"{int(pool['rejections'])} rejections "
                f"{json.dumps(pool.get('rejectionsByReason', {}))}, "
                f"fees charged {int(pool['feeTotal'])}")
        stages = entry.get("importStages") or {}
        if stages:
            lines += ["", "| import stage | n | mean ms | p50 ms | p95 ms |",
                      "|---|---|---|---|---|"]
            for stage, s in stages.items():
                lines.append(
                    f"| {stage} | {s['count']} | {s['mean_ms']} "
                    f"| {s['p50_ms']} | {s['p95_ms']} |"
                )
        lines.append("")
    replicas = {
        label: entry["readPlane"]
        for label, entry in report["per_node"].items()
        if entry.get("readPlane")
    }
    if replicas:
        lines += [
            "## Read plane",
            "",
            f"{report['fleet'].get('replicas', 0)} replica(s) served "
            f"{int(report['fleet'].get('replica_reads_total', 0))} "
            "verified read proofs.",
            "",
            "| replica | reads | proof p50 ms | proof p95 ms "
            "| justs verified | pairings | justs/pairing |",
            "|---|---|---|---|---|---|---|",
        ]
        for label, rp in replicas.items():
            lat = rp.get("proofLatency") or {}
            lines.append(
                f"| {label} | {int(rp['reads'])} "
                f"| {lat.get('p50_ms', 0)} | {lat.get('p95_ms', 0)} "
                f"| {int(rp['justificationsVerified'])} "
                f"| {int(rp['batchPairings'])} "
                f"| {rp['justsPerPairing']} |"
            )
        lines.append("")
    proof = report.get("proof") or {}
    if proof:
        lines += [
            "## Proof data plane",
            "",
            f"{proof['proofs']} proofs verified, "
            f"{proof['per_proof_ms']} ms/proof (wall-clock over "
            "combined checks).",
            "",
            "| stage | checks | total s | mean ms |",
            "|---|---|---|---|",
        ]
        for stage, s in proof.get("stages", {}).items():
            lines.append(
                f"| {stage} | {s['count']} | {s['total_s']} "
                f"| {s['mean_ms']} |"
            )
        lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", required=True,
                    help="comma-separated host:port RPC endpoints")
    ap.add_argument("--duration", type=float, default=15.0,
                    help="sampling window seconds")
    ap.add_argument("--poll", type=float, default=1.0)
    ap.add_argument("--out-json", default=None)
    ap.add_argument("--out-md", default=None)
    args = ap.parse_args(argv)

    nodes = []
    for part in filter(None, (p.strip() for p in args.nodes.split(","))):
        host, _, port = part.rpartition(":")
        nodes.append((host or "127.0.0.1", int(port)))
    collector = FleetCollector(nodes)
    deadline = time.time() + args.duration
    while time.time() < deadline:
        collector.sample()
        time.sleep(args.poll)
    collector.sample()
    report = collector.report()
    text = json.dumps(report, indent=2, sort_keys=True)
    if args.out_json:
        with open(args.out_json, "w") as fh:
            fh.write(text + "\n")
    if args.out_md:
        with open(args.out_md, "w") as fh:
            fh.write(to_markdown(report) + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
