"""Component-level profile of XlaBackend verify at bench geometry.

Times each stage of _combined_check separately on the real chip:
proofgen, rho derivation, mu combine (fr), sigma MSM, host XMD,
device SSWU map, grouped H-MSM, rho fold, u-side MSM, pairing —
then runs the fused single-program pipeline under profile_stages and
prints the host-vs-device overlap fraction from the stage histograms
(host_prep vs dispatch_wait; docs/perf.md explains how to read it).
"""

from __future__ import annotations

import os
import random
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def t(label, fn, *args, **kw):
    import jax

    t0 = time.perf_counter()
    out = fn(*args, **kw)
    try:
        jax.block_until_ready(out)
    except Exception:
        pass
    dt = time.perf_counter() - t0
    print(f"  {label:30s} {dt * 1000:9.1f} ms", file=sys.stderr, flush=True)
    return out, dt


def main():
    import jax

    from cess_tpu.ops import fr, g1, h2c, podr2
    from cess_tpu.ops import bls12_381 as bls
    from cess_tpu.ops.bls12_381 import G1Point, G2Point
    from cess_tpu.ops.podr2 import Challenge, Podr2Params
    from cess_tpu.proof import XlaBackend

    B = int(os.environ.get("PROF_PROOFS", "128"))
    params = Podr2Params()
    sk, pk = podr2.keygen(b"bench-tee")
    rnd = random.Random(0xBE7C)
    indices = tuple(sorted(rnd.sample(range(params.n), 47)))
    randoms = tuple(rnd.randbytes(20) for _ in indices)
    challenge = Challenge(indices=indices, randoms=randoms)
    coeffs = challenge.coefficients()

    names = [b"bench-frag-%08d" % i for i in range(B)]
    t0 = time.perf_counter()
    flat = podr2.chunk_points_batch([(nm, i) for nm in names for i in indices])
    h_pts = [flat[k * len(indices):(k + 1) * len(indices)] for k in range(B)]
    inner0 = g1.msm_grouped(h_pts, [coeffs] * B, bits=160)
    sigmas_pts = g1.scalar_mul_batch(inner0, [sk] * B)
    mu = [0] * params.s
    items = [(nm, challenge, podr2.Podr2Proof(s.to_bytes(), list(mu)))
             for nm, s in zip(names, sigmas_pts)]
    print(f"proofgen: {time.perf_counter() - t0:.2f}s", file=sys.stderr)

    backend = XlaBackend()
    podr2.chunk_point.cache_clear()

    # warm everything once end to end
    t0 = time.perf_counter()
    v = backend.verify_batch(pk, items, b"bench-seed", params)
    assert all(v)
    print(f"warm full verify: {time.perf_counter() - t0:.2f}s", file=sys.stderr)

    # Now break down stages (second run, compiled).
    backend._h_memo = {}
    print(f"B={B} breakdown:", file=sys.stderr)

    pk_point = G2Point.from_bytes(pk)
    sigmas = [G1Point.from_bytes(p.sigma) for _, _, p in items]
    batch_items = [podr2.BatchItem(n, c, p) for n, c, p in items]
    rhos, dt_rho = t("batch_rho", lambda: podr2.batch_rho(
        podr2.batch_transcript(b"bench-seed", batch_items), len(items)))

    mu_limbs = np.stack([fr.fr_to_limbs(p.mu) for _, _, p in items])
    _, dt_mu = t("mu combine (fr)", lambda: fr.combine_mu(rhos, mu_limbs))
    exps = fr.limbs_to_ints(fr.combine_mu(rhos, mu_limbs))

    lhs, dt_sig = t("sigma MSM (flat B)", lambda: g1.msm(sigmas, rhos, bits=128))

    # h2c front half: host XMD
    counts = [min(len(ch.indices), len(ch.randoms)) for _, ch, _ in items]
    name_ids = np.repeat(np.arange(B, dtype=np.uint32), counts)
    idxs = np.concatenate([np.asarray(ch.indices[:c], dtype=np.uint64)
                           for (_, ch, _), c in zip(items, counts)])
    (ulimbs_pack, dt_xmd) = t("host XMD (native)", lambda: h2c.u_for_pairs(
        names, name_ids, idxs, podr2.H_DST))
    u_limbs, sgn, exc = ulimbs_pack

    import jax.numpy as jnp
    (padded, m) = h2c._pad_pow2_lanes([u_limbs, sgn, exc], len(name_ids))
    u_d, s_d, e_d = (jnp.asarray(a) for a in padded)
    print(f"  (pairs={len(name_ids)}, padded lanes={m})", file=sys.stderr)
    _, dt_map = t("device SSWU map", lambda: h2c._map_pairs_kernel(u_d, s_d, e_d))
    (X, Y, Z) = h2c._map_pairs_kernel(u_d, s_d, e_d)

    # grouped MSM exactly as _h_inner_fold_device does
    def grouped():
        g = 1 << max(0, (max(counts) - 1).bit_length())
        Bp = 1 << max(0, (B - 1).bit_length())
        lane_map = np.zeros((Bp, g), dtype=np.int32)
        slimbs = np.zeros((Bp, g, g1.R_LIMBS), dtype=np.int32)
        limb_cache = {}

        def limbs_of(v):
            row = limb_cache.get(v)
            if row is None:
                row = g1.scalars_to_digits([v], g1.R_LIMBS)[:, 0]
                limb_cache[v] = row
            return row

        pos = 0
        for b, ((_, ch, _), cnt) in enumerate(zip(items, counts)):
            cf = ch.coefficients()[:cnt]
            for k, vv in enumerate(cf):
                lane_map[b, k] = pos + k
                slimbs[b, k] = limbs_of(vv * h2c.H_EFF)
            pos += cnt
        flat2 = lane_map.reshape(-1)
        Xg = jnp.take(X, jnp.asarray(flat2), axis=1)
        Yg = jnp.take(Y, jnp.asarray(flat2), axis=1)
        Zg = jnp.take(Z, jnp.asarray(flat2), axis=1)
        s = jnp.asarray(slimbs.reshape(Bp * g, g1.R_LIMBS).T)
        rX, rY, rZ = g1._msm_kernel(Xg, Yg, Zg, s, bits=224, group=g)
        return np.asarray(rX), np.asarray(rY), np.asarray(rZ)

    (rXYZ, dt_gmsm) = t("grouped H-MSM (scalar prep + kernel)", grouped)
    rX, rY, rZ = rXYZ
    inner = g1.projective_to_points(rX.T[:B], rY.T[:B], rZ.T[:B])

    _, dt_fold = t("rho fold MSM (flat B)", lambda: g1.msm(inner, rhos, bits=128))
    rhs = g1.msm(inner, rhos, bits=128)

    us = list(podr2.u_generators(params.s))
    _, dt_umsm = t("u-side MSM (s=265)", lambda: g1.msm(us, exps))
    rhs = rhs + g1.msm(us, exps)

    _, dt_pair = t("pairing check", lambda: bls.pairing_check(
        [(lhs, -bls.G2_GENERATOR), (rhs, pk_point)]))

    total = (dt_rho + dt_mu + dt_sig + dt_xmd + dt_map + dt_gmsm + dt_fold
             + dt_umsm + dt_pair)
    print(f"  {'SUM':30s} {total * 1000:9.1f} ms", file=sys.stderr)
    print(f"  per-proof if all scales: {total / B * 1000:.2f} ms",
          file=sys.stderr)

    # ---- fused pipeline pass + host/device overlap ------------------
    # The fused path runs each chunk's group math as one async device
    # program while the prefetch worker packs the next chunk's inputs;
    # host_prep is the un-overlappable host time on the critical path
    # and dispatch_wait the device time host prep failed to hide, so
    # host_prep / (host_prep + dispatch_wait) is the overlap fraction.
    from cess_tpu.proof.xla_backend import _stage_hists, proof_stage_registry

    fprof = XlaBackend(profile_stages=True, fused=True)
    podr2.chunk_point.cache_clear()
    t0 = time.perf_counter()
    assert all(fprof.verify_batch(pk, items, b"bench-seed", params))
    print(f"fused profiled pass: {time.perf_counter() - t0:.2f}s",
          file=sys.stderr)
    for k, v in sorted(fprof.stage_seconds.items(), key=lambda kv: -kv[1]):
        print(f"  fused {k:24s} {v * 1000:9.1f} ms", file=sys.stderr)
    host = fprof.stage_seconds.get("host_prep", 0.0)
    wait = fprof.stage_seconds.get("dispatch_wait", 0.0)
    if host + wait:
        print(f"  host/device overlap fraction: {host / (host + wait):.2f}",
              file=sys.stderr)

    # process-wide histogram totals (what a node exposes over RPC):
    proof_stage_registry()
    print("stage histogram totals (cess_proof_stage_*_seconds sums):",
          file=sys.stderr)
    for name, hist in sorted(_stage_hists.items()):
        print(f"  {name:24s} n={hist.n:5d} sum={hist.total:9.3f}s",
              file=sys.stderr)
    h = _stage_hists.get("host_prep")
    w = _stage_hists.get("dispatch_wait")
    if h is not None and w is not None and (h.total + w.total):
        print("  overlap fraction (histograms): "
              f"{h.total / (h.total + w.total):.2f}", file=sys.stderr)


if __name__ == "__main__":
    main()
